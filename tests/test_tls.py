"""TLS on HTTP/MySQL/PostgreSQL + PostgreSQL SCRAM-SHA-256 (reference
config/standalone.example.toml:14-27 per-server tls sections; pgwire
SCRAM auth)."""

import base64
import hashlib
import hmac
import json
import os
import socket
import ssl
import struct
import urllib.request

import pytest

# self-signed cert generation needs the cryptography package; containers
# without it (like the CI image) skip the whole TLS tier instead of
# erroring at collection
pytest.importorskip("cryptography")

from greptimedb_tpu.standalone import GreptimeDB  # noqa: E402
from greptimedb_tpu.utils.tls import (  # noqa: E402
    generate_self_signed, make_server_context,
)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    return generate_self_signed(str(d))


@pytest.fixture
def db():
    d = GreptimeDB()
    d.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
          "v DOUBLE, PRIMARY KEY (h))")
    d.sql("INSERT INTO t VALUES ('a', 1000, 1.5)")
    yield d
    d.close()


def _client_ctx():
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx


class TestHttpTls:
    def test_https_sql(self, db, certs):
        from greptimedb_tpu.servers.http import HttpServer

        srv = HttpServer(db, port=0,
                         ssl_context=make_server_context(*certs))
        srv.start()
        try:
            import urllib.parse

            q = urllib.parse.urlencode({"sql": "SELECT count(*) FROM t"})
            resp = urllib.request.urlopen(
                f"https://127.0.0.1:{srv.port}/v1/sql?{q}",
                context=_client_ctx())
            body = json.load(resp)
            assert body["output"][0]["records"]["rows"] == [[1]]
        finally:
            srv.stop()


class TestPgTls:
    def _ssl_connect(self, port):
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.sendall(struct.pack(">II", 8, 80877103))  # SSLRequest
        assert s.recv(1) == b"S"
        return _client_ctx().wrap_socket(s)

    def test_sslrequest_upgrade_and_query(self, db, certs):
        from greptimedb_tpu.servers.postgres import PostgresServer

        pg = PostgresServer(db, port=0,
                            ssl_context=make_server_context(*certs))
        pg.start()
        try:
            s = self._ssl_connect(pg.port)
            body = struct.pack(">I", 196608) + b"user\x00root\x00\x00"
            s.sendall(struct.pack(">I", len(body) + 4) + body)
            # drain to ReadyForQuery
            def read_msg():
                tag = s.recv(1)
                ln = struct.unpack(">I", _recvn(s, 4))[0]
                return tag, _recvn(s, ln - 4)

            def _recvn(sk, n):
                buf = b""
                while len(buf) < n:
                    c = sk.recv(n - len(buf))
                    assert c
                    buf += c
                return buf

            while True:
                tag, _ = read_msg()
                if tag == b"Z":
                    break
            q = b"SELECT count(*) FROM t\x00"
            s.sendall(b"Q" + struct.pack(">I", len(q) + 4) + q)
            rows = []
            while True:
                tag, bd = read_msg()
                if tag == b"D":
                    rows.append(bd)
                if tag == b"Z":
                    break
            assert len(rows) == 1 and rows[0].endswith(b"1")
            s.close()
        finally:
            pg.stop()

    def test_decline_without_ctx(self, db):
        from greptimedb_tpu.servers.postgres import PostgresServer

        pg = PostgresServer(db, port=0)
        pg.start()
        try:
            s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
            s.sendall(struct.pack(">II", 8, 80877103))
            assert s.recv(1) == b"N"
            s.close()
        finally:
            pg.stop()


def _scram_client_exchange(sock, user, password):
    """Minimal SCRAM-SHA-256 client over an open pg socket (RFC 7677)."""
    def read_msg():
        tag = sock.recv(1)
        ln = struct.unpack(">I", _recvn(4))[0]
        return tag, _recvn(ln - 4)

    def _recvn(n):
        buf = b""
        while len(buf) < n:
            c = sock.recv(n - len(buf))
            assert c, "closed"
            buf += c
        return buf

    body = struct.pack(">I", 196608) + (
        b"user\x00" + user.encode() + b"\x00\x00")
    sock.sendall(struct.pack(">I", len(body) + 4) + body)
    tag, bd = read_msg()
    assert tag == b"R" and struct.unpack(">I", bd[:4])[0] == 10
    assert b"SCRAM-SHA-256" in bd
    cnonce = base64.b64encode(os.urandom(18)).decode()
    cf_bare = f"n={user},r={cnonce}"
    payload = ("n,," + cf_bare).encode()
    sasl = (b"SCRAM-SHA-256\x00" + struct.pack(">i", len(payload))
            + payload)
    sock.sendall(b"p" + struct.pack(">I", len(sasl) + 4) + sasl)
    tag, bd = read_msg()
    if tag == b"E":
        return False, None
    assert struct.unpack(">I", bd[:4])[0] == 11
    server_first = bd[4:].decode()
    attrs = dict(p.split("=", 1) for p in server_first.split(","))
    nonce, salt, it = attrs["r"], base64.b64decode(attrs["s"]), int(attrs["i"])
    assert nonce.startswith(cnonce)
    salted = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, it)
    ckey = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
    skey = hashlib.sha256(ckey).digest()
    cf_wo = f"c=biws,r={nonce}"
    auth_msg = ",".join([cf_bare, server_first, cf_wo]).encode()
    csig = hmac.new(skey, auth_msg, hashlib.sha256).digest()
    proof = bytes(a ^ b for a, b in zip(ckey, csig))
    final = (cf_wo + ",p=" + base64.b64encode(proof).decode()).encode()
    sock.sendall(b"p" + struct.pack(">I", len(final) + 4) + final)
    tag, bd = read_msg()
    if tag == b"E":
        return False, None
    assert struct.unpack(">I", bd[:4])[0] == 12
    server_sig = dict(
        p.split("=", 1) for p in bd[4:].decode().split(","))["v"]
    # drain to ReadyForQuery
    while True:
        tag, _bd = read_msg()
        if tag == b"Z":
            break
    return True, server_sig


class TestPgScram:
    @pytest.fixture
    def auth_db(self):
        from greptimedb_tpu.utils.auth import StaticUserProvider

        d = GreptimeDB()
        d.user_provider = StaticUserProvider({"alice": "wonder=land:42"})
        d.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "v DOUBLE, PRIMARY KEY (h))")
        yield d
        d.close()

    def test_scram_success_and_server_signature(self, auth_db):
        from greptimedb_tpu.servers.postgres import PostgresServer

        pg = PostgresServer(auth_db, port=0, auth_mode="scram")
        pg.start()
        try:
            s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
            ok, server_sig = _scram_client_exchange(
                s, "alice", "wonder=land:42")
            assert ok and server_sig
            s.close()
        finally:
            pg.stop()

    def test_scram_wrong_password(self, auth_db):
        from greptimedb_tpu.servers.postgres import PostgresServer

        pg = PostgresServer(auth_db, port=0, auth_mode="scram")
        pg.start()
        try:
            s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
            ok, _ = _scram_client_exchange(s, "alice", "nope")
            assert not ok
            s.close()
        finally:
            pg.stop()


class TestMysqlTls:
    def test_starttls_handshake_and_query(self, db, certs):
        from greptimedb_tpu.servers.mysql import MysqlServer

        srv = MysqlServer(db, port=0,
                          ssl_context=make_server_context(*certs))
        srv.start()
        try:
            raw = socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=5)

            def read_pkt(sk):
                hdr = b""
                while len(hdr) < 4:
                    hdr += sk.recv(4 - len(hdr))
                ln = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
                body = b""
                while len(body) < ln:
                    body += sk.recv(ln - len(body))
                return body, hdr[3]

            greeting, _seq = read_pkt(raw)
            # server must advertise CLIENT_SSL (0x800) in the low caps
            nul = greeting.index(b"\x00", 1)
            lo = struct.unpack("<H", greeting[nul + 1 + 4 + 8 + 1:][:2])[0]
            assert lo & 0x800
            # SSLRequest: caps incl CLIENT_SSL, short packet, seq 1
            caps = 0x200 | 0x8000 | 0x1 | 0x800
            sslreq = struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23
            raw.sendall(bytes([len(sslreq) & 0xFF, 0, 0, 1]) + sslreq)
            tls = _client_ctx().wrap_socket(raw)
            # real handshake response over TLS, seq 2
            resp = (struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23
                    + b"root\x00" + b"\x00")
            tls.sendall(bytes([len(resp) & 0xFF, 0, 0, 2]) + resp)
            ok, _ = read_pkt(tls)
            assert ok[0] == 0x00, ok
            # COM_QUERY over TLS
            q = b"\x03" + b"SELECT count(*) FROM t"
            tls.sendall(bytes([len(q) & 0xFF, 0, 0, 0]) + q)
            col_count, _ = read_pkt(tls)
            assert col_count == b"\x01"
            tls.close()
        finally:
            srv.stop()


class TestTlsRequire:
    def test_pg_rejects_plaintext_when_required(self, db, certs):
        from greptimedb_tpu.servers.postgres import PostgresServer

        pg = PostgresServer(db, port=0,
                            ssl_context=make_server_context(*certs),
                            tls_require=True)
        pg.start()
        try:
            s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
            body = struct.pack(">I", 196608) + b"user\x00root\x00\x00"
            s.sendall(struct.pack(">I", len(body) + 4) + body)
            tag = s.recv(1)
            assert tag == b"E"  # ErrorResponse, not auth/ready
            s.close()
        finally:
            pg.stop()

    def test_mysql_rejects_plaintext_when_required(self, db, certs):
        from greptimedb_tpu.servers.mysql import MysqlServer

        srv = MysqlServer(db, port=0,
                          ssl_context=make_server_context(*certs),
                          tls_require=True)
        srv.start()
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            hdr = b""
            while len(hdr) < 4:
                hdr += s.recv(4 - len(hdr))
            ln = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
            while ln:
                ln -= len(s.recv(ln))
            caps = 0x200 | 0x8000 | 0x1  # no CLIENT_SSL
            resp = (struct.pack("<IIB", caps, 1 << 24, 0x21) + b"\x00" * 23
                    + b"root\x00" + b"\x00")
            s.sendall(bytes([len(resp) & 0xFF, 0, 0, 1]) + resp)
            hdr = b""
            while len(hdr) < 4:
                hdr += s.recv(4 - len(hdr))
            first = s.recv(1)
            assert first == b"\xff"  # ERR packet
            s.close()
        finally:
            srv.stop()

    def test_require_mode_needs_cert(self, tmp_path):
        from greptimedb_tpu.utils.tls import TlsConfig, context_from_config

        with pytest.raises(ValueError):
            context_from_config(TlsConfig(mode="require"), str(tmp_path))


def test_scram_over_tls_combined(certs):
    """The production shape: SSLRequest upgrade, then SCRAM-SHA-256 over
    the encrypted stream, then a query."""
    from greptimedb_tpu.servers.postgres import PostgresServer
    from greptimedb_tpu.utils.auth import StaticUserProvider

    db = GreptimeDB()
    db.user_provider = StaticUserProvider({"bob": "s3cr3t"})
    db.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
           "v DOUBLE, PRIMARY KEY (h))")
    db.sql("INSERT INTO t VALUES ('a', 1000, 7.5)")
    pg = PostgresServer(db, port=0, ssl_context=make_server_context(*certs),
                        auth_mode="scram", tls_require=True)
    pg.start()
    try:
        raw = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
        raw.sendall(struct.pack(">II", 8, 80877103))
        assert raw.recv(1) == b"S"
        s = _client_ctx().wrap_socket(raw)
        ok, server_sig = _scram_client_exchange(s, "bob", "s3cr3t")
        assert ok and server_sig
        q = b"SELECT v FROM t\x00"
        s.sendall(b"Q" + struct.pack(">I", len(q) + 4) + q)
        saw_row = False
        while True:
            tag = s.recv(1)
            ln = struct.unpack(">I", _recvn_sock(s, 4))[0]
            body = _recvn_sock(s, ln - 4)
            if tag == b"D":
                saw_row = body.endswith(b"7.5")
            if tag == b"Z":
                break
        assert saw_row
        s.close()
    finally:
        pg.stop()
        db.close()


def _recvn_sock(sock, n):
    buf = b""
    while len(buf) < n:
        c = sock.recv(n - len(buf))
        assert c, "closed"
        buf += c
    return buf
