"""HTTP protocol server tests: real sockets, real wire formats.

Mirrors the reference's protocol integration tests
(tests-integration/tests/http.rs): SQL envelope, Prometheus API formats,
line protocol and remote write bodies.
"""

import json
import struct
import urllib.parse
import urllib.request

import numpy as np
import pytest

from greptimedb_tpu.servers import HttpServer
from greptimedb_tpu.servers.protocols import parse_line_protocol, parse_remote_write
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.utils import snappy


@pytest.fixture(scope="module")
def server():
    db = GreptimeDB()
    srv = HttpServer(db, port=0)
    srv.start()
    yield srv
    srv.stop()
    db.close()


def http(server, path, method="GET", body=None, headers=None, form=None):
    url = f"http://127.0.0.1:{server.port}{path}"
    if form is not None:
        body = urllib.parse.urlencode(form).encode()
        headers = dict(headers or {})
        headers["Content-Type"] = "application/x-www-form-urlencoded"
        method = "POST"
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req) as resp:
            data = resp.read()
            return resp.status, data
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestSnappy:
    def test_roundtrip(self):
        for payload in [b"", b"x", b"hello world" * 100, bytes(range(256)) * 50]:
            assert snappy.decompress(snappy.compress(payload)) == payload

    def test_copy_elements(self):
        # hand-built: literal "abcd" + 1-byte-offset copy of 4 from offset 4
        body = bytes([8]) + bytes([(4 - 1) << 2]) + b"abcd" + bytes(
            [0b001 | ((4 - 4) << 2)][0:1]
        )
        # tag: type=1, len=4 -> ((4-4)<<2)|1 = 1; offset byte = 4
        body = bytes([8, (4 - 1) << 2]) + b"abcd" + bytes([1, 4])
        assert snappy.decompress(body) == b"abcdabcd"

    def test_corrupt(self):
        with pytest.raises(ValueError):
            snappy.decompress(b"\x10\xff\xff")


class TestLineProtocol:
    def test_parse(self):
        out = parse_line_protocol(
            'cpu,host=h1,region=us value=0.5,count=3i 1700000000000000000\n'
            'cpu,host=h2 value=1.5 1700000001000000000\n'
            'mem,host=h1 used=12.5 1700000000000000000\n'
        )
        assert set(out) == {"cpu", "mem"}
        cpu = out["cpu"]
        assert cpu["__tags__"] == ["host", "region"]
        assert cpu["host"] == ["h1", "h2"]
        assert cpu["region"] == ["us", None]
        assert cpu["value"] == [0.5, 1.5]
        assert cpu["count"] == [3, None]
        assert cpu["ts"] == [1700000000000, 1700000001000]

    def test_escapes_and_types(self):
        out = parse_line_protocol(
            'my\\ table,tag=va\\,lue str="quoted \\"x\\"",b=t 1000',
            precision="ms",
        )
        t = out["my table"]
        assert t["tag"] == ["va,lue"]
        assert t["str"] == ['quoted "x"']
        assert t["b"] == [True]
        assert t["ts"] == [1000]

    def test_bad_lines(self):
        from greptimedb_tpu.errors import InvalidArguments

        for bad in ["cpu", "cpu,host=h1", "cpu value=", ",host=x value=1"]:
            with pytest.raises(InvalidArguments):
                parse_line_protocol(bad)


from greptimedb_tpu.utils.proto import (
    pb_len as _pb_len, pb_varint as _pb_varint,
)


def make_write_request(series: list[tuple[dict, list[tuple[float, int]]]]) -> bytes:
    body = b""
    for labels, samples in series:
        ts_msg = b""
        for name, value in labels.items():
            label = _pb_len(1, name.encode()) + _pb_len(2, value.encode())
            ts_msg += _pb_len(1, label)
        for val, ts in samples:
            sample = (
                _pb_varint((1 << 3) | 1) + struct.pack("<d", val)
                + _pb_varint(2 << 3) + _pb_varint(ts & ((1 << 64) - 1))
            )
            ts_msg += _pb_len(2, sample)
        body += _pb_len(1, ts_msg)
    return body


class TestRemoteWriteCodec:
    def test_parse(self):
        pb = make_write_request([
            ({"__name__": "up", "job": "api"}, [(1.0, 1000), (0.0, 2000)]),
            ({"__name__": "up", "job": "web"}, [(1.0, 1000)]),
        ])
        out = parse_remote_write(pb)
        assert set(out) == {"up"}
        up = out["up"]
        # container-agnostic: the vectorized parser returns np arrays /
        # DictColumn, the legacy (=off) parser plain lists — same VALUES
        assert list(up["job"]) == ["api", "api", "web"]
        assert list(up["val"]) == [1.0, 0.0, 1.0]
        assert list(up["ts"]) == [1000, 2000, 1000]


class TestHttpApi:
    def test_sql_roundtrip(self, server):
        code, _ = http(server, "/v1/sql", form={
            "sql": "CREATE TABLE web (host STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " hits DOUBLE, PRIMARY KEY (host))"})
        assert code == 200
        code, _ = http(server, "/v1/sql", form={
            "sql": "INSERT INTO web VALUES ('a', 1000, 5.0), ('b', 2000, 7.0)"})
        assert code == 200
        code, raw = http(
            server,
            "/v1/sql?" + urllib.parse.urlencode(
                {"sql": "SELECT host, hits FROM web ORDER BY host"}),
        )
        assert code == 200
        body = json.loads(raw)
        assert body["code"] == 0
        rec = body["output"][0]["records"]
        assert [c["name"] for c in rec["schema"]["column_schemas"]] == ["host", "hits"]
        assert rec["rows"] == [["a", 5.0], ["b", 7.0]]

    def test_sql_errors(self, server):
        code, raw = http(server, "/v1/sql", form={"sql": "SELEC 1"})
        assert code == 400
        assert json.loads(raw)["code"] != 0
        code, raw = http(server, "/v1/sql", form={"sql": "SELECT * FROM nope"})
        assert code == 404
        code, raw = http(server, "/v1/sql")
        assert code == 400

    def test_influx_write_and_query(self, server):
        lp = (
            "weather,city=sf temp=13.5 1700000000000\n"
            "weather,city=nyc temp=2.0 1700000000000\n"
        )
        code, _ = http(server, "/v1/influxdb/api/v2/write?precision=ms",
                       method="POST", body=lp.encode())
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT city, temp FROM weather ORDER BY city"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["nyc", 2.0], ["sf", 13.5]]

    def test_arrow_bulk_write_and_query(self, server):
        import io

        import pyarrow as pa

        t = pa.table({
            "city": pa.array(["sf", "nyc"]).dictionary_encode(),
            "ts": np.array([1700000000000, 1700000000000], dtype=np.int64),
            "temp": np.array([13.5, 2.0]),
        })
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        code, raw = http(server, "/v1/arrow/write?table=weather_bulk",
                         method="POST", body=sink.getvalue())
        assert code == 200
        assert json.loads(raw)["rows"] == 2
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT city, temp FROM weather_bulk ORDER BY city"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["nyc", 2.0], ["sf", 13.5]]
        # missing ?table= and junk bodies surface as 400, not 500
        code, _ = http(server, "/v1/arrow/write", method="POST",
                       body=sink.getvalue())
        assert code == 400
        code, _ = http(server, "/v1/arrow/write?table=x", method="POST",
                       body=b"junk")
        assert code == 400

    def test_influx_schema_extension(self, server):
        http(server, "/v1/influxdb/api/v2/write?precision=ms",
             method="POST", body=b"weather,city=sf humidity=80.0 1700000001000")
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT humidity FROM weather WHERE city = 'sf' ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [[None], [80.0]]

    def test_remote_write_and_prom_query(self, server):
        ts0 = 1700000000000
        pb = make_write_request([
            ({"__name__": "http_total", "job": "api"},
             [(float(5 * i), ts0 + i * 10_000) for i in range(60)]),
        ])
        code, _ = http(server, "/v1/prometheus/write", method="POST",
                       body=snappy.compress(pb),
                       headers={"Content-Encoding": "snappy"})
        assert code == 204
        q = urllib.parse.urlencode({
            "query": "rate(http_total[5m])",
            "start": str(ts0 / 1000 + 300), "end": str(ts0 / 1000 + 500),
            "step": "100",
        })
        code, raw = http(server, f"/v1/prometheus/api/v1/query_range?{q}")
        assert code == 200
        body = json.loads(raw)
        assert body["status"] == "success"
        series = body["data"]["result"]
        assert len(series) == 1
        assert series[0]["metric"] == {"job": "api"}
        for _t, v in series[0]["values"]:
            assert float(v) == pytest.approx(0.5, rel=1e-5)

    def test_prom_instant_query(self, server):
        q = urllib.parse.urlencode({
            "query": "http_total", "time": str(1700000000000 / 1000 + 590),
        })
        code, raw = http(server, f"/v1/prometheus/api/v1/query?{q}")
        body = json.loads(raw)
        assert body["data"]["resultType"] == "vector"
        assert len(body["data"]["result"]) == 1

    def test_prom_metadata(self, server):
        code, raw = http(server, "/v1/prometheus/api/v1/labels")
        data = json.loads(raw)["data"]
        assert "__name__" in data and "job" in data
        code, raw = http(server, "/v1/prometheus/api/v1/label/__name__/values")
        assert "http_total" in json.loads(raw)["data"]
        code, raw = http(server, "/v1/prometheus/api/v1/label/job/values")
        assert "api" in json.loads(raw)["data"]
        q = urllib.parse.urlencode({"match[]": "http_total"})
        code, raw = http(server, f"/v1/prometheus/api/v1/series?{q}")
        data = json.loads(raw)["data"]
        assert {"__name__": "http_total", "job": "api"} in data

    def test_promql_native_endpoint(self, server):
        q = urllib.parse.urlencode({
            "query": "http_total", "start": str(1700000000000 / 1000 + 100),
            "end": str(1700000000000 / 1000 + 100), "step": "60",
        })
        code, raw = http(server, f"/v1/promql?{q}")
        assert code == 200
        body = json.loads(raw)
        rec = body["output"][0]["records"]
        assert rec["schema"]["column_schemas"][0]["name"] == "job"

    def test_admin_endpoints(self, server):
        code, _ = http(server, "/health")
        assert code == 200
        code, raw = http(server, "/metrics")
        assert code == 200
        assert b"greptime_http_requests_total" in raw
        code, raw = http(server, "/config")
        assert code == 200 and b"data_home" in raw
        code, raw = http(server, "/status")
        assert code == 200 and b"devices" in raw and b"memory" in raw

    def test_dashboard_served(self, server):
        code, raw = http(server, "/dashboard")
        assert code == 200
        # self-contained page wired to the real endpoints
        assert b"<!doctype html>" in raw and b"greptimedb-tpu" in raw
        for endpoint in (b"/v1/sql", b"/v1/prometheus/api/v1/query_range",
                         b"/status"):
            assert endpoint in raw
        assert b'src="http' not in raw  # no external assets

    def test_bad_remote_write_body(self, server):
        code, _ = http(server, "/v1/prometheus/write", method="POST",
                       body=b"\xff\xfe\xfd",
                       headers={"Content-Encoding": "snappy"})
        assert code == 400


class TestReviewRegressions:
    def test_new_tag_added_online_not_dropped(self, server):
        # online tag addition (reference alter-on-demand): the second
        # write's new label column is ADDED; earlier rows read ""
        http(server, "/v1/influxdb/api/v2/write?precision=ms",
             method="POST", body=b"ttags,host=a v=1.0 1000")
        code, _raw = http(server, "/v1/influxdb/api/v2/write?precision=ms",
                          method="POST",
                          body=b"ttags,host=a,region=us v=2.0 2000")
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT host, region, v FROM ttags ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["a", "", 1.0], ["a", "us", 2.0]]

    def test_bad_lp_timestamp_is_400(self, server):
        code, _ = http(server, "/v1/influxdb/write", method="POST",
                       body=b"cpu val=1 notanumber")
        assert code == 400

    def test_ns_timestamp_exact(self):
        ns = 1700000000123999999  # truncates to ...123 ms exactly
        out = parse_line_protocol(f"m v=1 {ns}")
        assert out["m"]["ts"] == [1700000000123]

    def test_snappy_overlapping_copy_fast(self):
        # run-length style: 1-byte literal + long overlapping copy
        data = b"a" * 10000
        assert snappy.decompress(snappy.compress(data)) == data
        import time
        big = bytes(np.random.default_rng(0).integers(65, 91, 2_000_000, dtype=np.uint8))
        t0 = time.time()
        assert snappy.decompress(snappy.compress(big)) == big
        assert time.time() - t0 < 2.0

    def test_partitioned_ingest_and_label_values(self, server):
        http(server, "/v1/sql", form={
            "sql": "CREATE TABLE ppt (host STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " val DOUBLE, PRIMARY KEY (host))"
                   " PARTITION ON COLUMNS (host) (host < 'm', host >= 'm')"})
        lp = "ppt,host=alpha val=1 1000\nppt,host=zulu val=2 1000\n"
        code, _ = http(server, "/v1/influxdb/write?precision=ms",
                       method="POST", body=lp.encode())
        assert code == 204
        db = server.db
        info = db.catalog.get_table("public", "ppt")
        r1_hosts = set(db.regions.regions[info.region_ids[1]].scan_host()["host"])
        assert "zulu" in r1_hosts  # routed, not dumped into region 0
        code, raw = http(server, "/v1/prometheus/api/v1/label/host/values")
        vals = json.loads(raw)["data"]
        assert "alpha" in vals and "zulu" in vals


def _otlp_metrics_request():
    """Build a minimal ExportMetricsServiceRequest: one gauge + one histogram."""
    def kv(key, sval):
        anyv = _pb_len(1, sval.encode())
        return _pb_len(1, key.encode()) + _pb_len(2, anyv)

    def fixed64(field, val_bytes):
        return _pb_varint((field << 3) | 1) + val_bytes

    ts_ns = 1700000000 * 10**9
    # gauge point: attrs {pod=p1}, t, as_double 42.5
    pt = (_pb_len(7, kv("pod", "p1"))
          + fixed64(3, struct.pack("<Q", ts_ns))
          + fixed64(4, struct.pack("<d", 42.5)))
    gauge = _pb_len(1, pt)
    metric1 = _pb_len(1, b"cpu_usage") + _pb_len(5, gauge)
    # histogram point: count=6, sum=7.5, buckets [1,2,3] bounds [0.1, 1]
    hp = (_pb_len(9, kv("pod", "p1"))
          + fixed64(3, struct.pack("<Q", ts_ns))
          + fixed64(4, struct.pack("<Q", 6))
          + fixed64(5, struct.pack("<d", 7.5))
          + _pb_len(6, struct.pack("<QQQ", 1, 2, 3))
          + _pb_len(7, struct.pack("<dd", 0.1, 1.0)))
    hist = _pb_len(1, hp)
    metric2 = _pb_len(1, b"req_latency") + _pb_len(9, hist)
    scope_metrics = _pb_len(2, metric1) + _pb_len(2, metric2)
    resource = _pb_len(1, kv("svc", "api"))
    rm = _pb_len(1, resource) + _pb_len(2, scope_metrics)
    return _pb_len(1, rm)


class TestOtlpAndLoki:
    def test_otlp_metrics(self, server):
        body = _otlp_metrics_request()
        code, raw = http(server, "/v1/otlp/v1/metrics", method="POST", body=body,
                         headers={"Content-Type": "application/x-protobuf"})
        assert code == 200, raw
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT pod, svc, val FROM cpu_usage"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["p1", "api", 42.5]]
        # histogram exploded prom-style with cumulative buckets
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT le, val FROM req_latency_bucket ORDER BY val"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["0.1", 1.0], ["1.0", 3.0], ["+Inf", 6.0]]
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT val FROM req_latency_count"}))
        assert json.loads(raw)["output"][0]["records"]["rows"] == [[6.0]]
        # and histogram_quantile works over the bucket table
        code, raw = http(server, "/v1/prometheus/api/v1/query?" +
                         urllib.parse.urlencode({
                             "query": "histogram_quantile(0.5, req_latency_bucket)",
                             "time": str(1700000000 + 10)}))
        body = json.loads(raw)
        assert body["status"] == "success"
        assert len(body["data"]["result"]) == 1

    def test_loki_push_and_query(self, server):
        payload = {
            "streams": [{
                "stream": {"app": "web", "level": "error"},
                "values": [
                    ["1700000000000000000", "boom happened"],
                    ["1700000001000000000", "again"],
                ],
            }]
        }
        code, _ = http(server, "/v1/loki/api/v1/push", method="POST",
                       body=json.dumps(payload).encode(),
                       headers={"Content-Type": "application/json"})
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT app, level, line FROM loki_logs ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["web", "error", "boom happened"],
                        ["web", "error", "again"]]

    def test_loki_protobuf_push(self, server):
        # promtail wire form: snappy(logproto.PushRequest)
        def varint(v):
            out = b""
            while True:
                b7 = v & 0x7F
                v >>= 7
                out += bytes([b7 | (0x80 if v else 0)])
                if not v:
                    return out

        def field(num, payload):
            return varint((num << 3) | 2) + varint(len(payload)) + payload

        # EntryAdapter: timestamp (field 1, message) + line (field 2)
        ts_msg = (varint(1 << 3 | 0) + varint(1700000099)
                  + varint(2 << 3 | 0) + varint(500_000_000))
        entry = field(1, ts_msg) + field(2, b"proto boom")
        stream = field(1, b'{job="api", env="prod"}') + field(2, entry)
        push = field(1, stream)
        code, _ = http(server, "/v1/loki/api/v1/push", method="POST",
                       body=snappy.compress(push),
                       headers={"Content-Type": "application/x-protobuf"})
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT job, env, line FROM loki_logs"
                    " WHERE job = 'api'"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["api", "prod", "proto boom"]]

    def test_loki_bad_payload(self, server):
        code, _ = http(server, "/v1/loki/api/v1/push", method="POST",
                       body=b"not json",
                       headers={"Content-Type": "application/json"})
        assert code == 400

    def test_otel_arrow_metrics(self, server):
        import io

        import pyarrow as pa
        import pyarrow.ipc as pa_ipc

        tbl = pa.table({
            "name": ["otap_cpu", "otap_cpu", "otap_mem"],
            "time_unix_nano": [1700000000_000000000, 1700000001_000000000,
                               1700000000_000000000],
            "value": [0.5, 0.7, 1024.0],
            "host": ["h1", "h2", "h1"],
        })
        buf = io.BytesIO()
        with pa_ipc.new_stream(buf, tbl.schema) as w:
            w.write_table(tbl)
        code, raw = http(server, "/v1/otel-arrow/v1/metrics", method="POST",
                         body=buf.getvalue())
        assert code == 200 and json.loads(raw)["rows"] == 3
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT host, val FROM otap_cpu ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert [r[0] for r in rows] == ["h1", "h2"]
        assert rows[0][1] == pytest.approx(0.5)
        assert rows[1][1] == pytest.approx(0.7)  # f32 device storage
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT val FROM otap_mem"}))
        assert json.loads(raw)["output"][0]["records"]["rows"] == [[1024.0]]

    def test_otel_arrow_bad_body(self, server):
        code, _ = http(server, "/v1/otel-arrow/v1/metrics", method="POST",
                       body=b"not arrow")
        assert code == 400

    def test_loki_bad_entry_and_gzip(self, server):
        payload = {"streams": [{"stream": {"a": "b"},
                                "values": [["not-a-number", "line"]]}]}
        code, _ = http(server, "/v1/loki/api/v1/push", method="POST",
                       body=json.dumps(payload).encode(),
                       headers={"Content-Type": "application/json"})
        assert code == 400
        code, _ = http(server, "/v1/otlp/v1/metrics", method="POST",
                       body=b"\x1f\x8b truncated",
                       headers={"Content-Encoding": "gzip"})
        assert code == 400

    def test_reserved_label_names(self):
        # loki labels named ts/line must not corrupt the batch (fresh db:
        # loki_logs schema is created from the first batch's labels)
        db = GreptimeDB()
        srv = HttpServer(db, port=0)
        srv.start()
        try:
            payload = {"streams": [{"stream": {"ts": "oops", "line": "also"},
                                    "values": [["1700000000000000000", "msg"]]}]}
            code, _ = http(srv, "/v1/loki/api/v1/push", method="POST",
                           body=json.dumps(payload).encode(),
                           headers={"Content-Type": "application/json"})
            assert code == 204
            code, raw = http(srv, "/v1/sql?" + urllib.parse.urlencode(
                {"sql": "SELECT ts_label, line_label, line FROM loki_logs"
                        " WHERE ts_label = 'oops'"}))
            rows = json.loads(raw)["output"][0]["records"]["rows"]
            assert rows == [["oops", "also", "msg"]]
        finally:
            srv.stop()
            db.close()


class TestMoreProtocols:
    def test_opentsdb_put(self, server):
        pts = [{"metric": "sys_cpu", "timestamp": 1700000000,
                "value": 42.5, "tags": {"host": "web01"}},
               {"metric": "sys_cpu", "timestamp": 1700000010,
                "value": 43.0, "tags": {"host": "web01"}}]
        code, _ = http(server, "/v1/opentsdb/api/put", method="POST",
                       body=json.dumps(pts).encode())
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT host, val FROM sys_cpu ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["web01", 42.5], ["web01", 43.0]]
        code, _ = http(server, "/v1/opentsdb/api/put", method="POST",
                       body=b"[{\"nope\": 1}]")
        assert code == 400

    def test_es_bulk(self, server):
        nd = (
            '{"index": {"_index": "app-logs"}}\n'
            '{"@timestamp": "2026-01-01T00:00:00Z", "message": "hello"}\n'
            '{"create": {"_index": "app-logs"}}\n'
            '{"@timestamp": "2026-01-01T00:00:01Z", "message": "world"}\n'
        )
        code, raw = http(server, "/v1/elasticsearch/_bulk", method="POST",
                         body=nd.encode())
        assert code == 200 and json.loads(raw)["errors"] is False
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT doc FROM app_logs ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert len(rows) == 2 and "hello" in rows[0][0]
        code, raw = http(server, "/v1/elasticsearch/")
        assert json.loads(raw)["version"]["number"].startswith("8.")

    def test_splunk_hec(self, server):
        events = (
            '{"time": 1700000000.5, "sourcetype": "access",'
            ' "event": "GET /"}'
            '{"time": 1700000001, "sourcetype": "access",'
            ' "event": {"msg": "structured"}}'
        )
        code, raw = http(server, "/v1/splunk/services/collector",
                         method="POST", body=events.encode())
        assert code == 200 and json.loads(raw)["code"] == 0
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT sourcetype, event FROM splunk_events ORDER BY ts"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows[0] == ["access", "GET /"]
        assert "structured" in rows[1][1]

    def test_opentsdb_reserved_tag_and_bad_ts(self, server):
        pts = [{"metric": "rm1", "timestamp": 1700000000, "value": 1.0,
                "tags": {"ts": "x", "val": "y"}}]
        code, _ = http(server, "/v1/opentsdb/api/put", method="POST",
                       body=json.dumps(pts).encode())
        assert code == 204
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT ts_tag, val_tag, val FROM rm1"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert rows == [["x", "y", 1.0]]
        code, _ = http(server, "/v1/opentsdb/api/put", method="POST",
                       body=b'{"metric":"m","timestamp":"abc","value":1}')
        assert code == 400

    def test_es_bulk_desync_recovery(self, server):
        nd = ('{"index": {"_index": "dsync"}}\n'
              'not json at all {{{\n'
              '{"index": {"_index": "dsync"}}\n'
              '{"@timestamp": "2026-01-01T00:00:00Z", "message": "real"}\n')
        code, _ = http(server, "/v1/elasticsearch/_bulk", method="POST",
                       body=nd.encode())
        assert code == 200
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT doc FROM dsync"}))
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert len(rows) == 1 and "real" in rows[0][0]

    def test_splunk_bad_payload(self, server):
        code, _ = http(server, "/v1/splunk/services/collector",
                       method="POST", body=b'{"time":1} {{{garbage')
        assert code == 400


def _otlp_traces_request():
    """ExportTraceServiceRequest: 2 spans in one trace + 1 in another."""
    def kv(key, sval):
        return _pb_len(1, key.encode()) + _pb_len(2, _pb_len(1, sval.encode()))

    def fixed64(field, value):
        return _pb_varint((field << 3) | 1) + struct.pack("<Q", value)

    t0 = 1700000000 * 10**9

    def span(tid, sid, parent, name, start, dur, kind=2):
        s = (_pb_len(1, bytes.fromhex(tid)) + _pb_len(2, bytes.fromhex(sid))
             + (_pb_len(4, bytes.fromhex(parent)) if parent else b"")
             + _pb_len(5, name.encode())
             + _pb_varint(6 << 3) + _pb_varint(kind)
             + fixed64(7, start) + fixed64(8, start + dur)
             + _pb_len(9, kv("http.method", "GET")))
        return _pb_len(2, s)

    tid1 = "0102030405060708090a0b0c0d0e0f10"
    tid2 = "1112131415161718191a1b1c1d1e1f20"
    spans = (span(tid1, "0102030405060708", "", "GET /api", t0, 50_000_000)
             + span(tid1, "1112131415161718", "0102030405060708", "db.query",
                    t0 + 10**7, 20_000_000, kind=3)
             + span(tid2, "2122232425262728", "", "GET /other", t0 + 10**9,
                    5_000_000))
    # ScopeSpans message = concatenated field-2 Span entries; ResourceSpans
    # wraps it once as ITS field 2
    resource = _pb_len(1, kv("service.name", "api-server"))
    rs = _pb_len(1, resource) + _pb_len(2, spans)
    return _pb_len(1, rs), tid1, tid2


class TestTraces:
    def test_otlp_traces_and_jaeger_api(self, server):
        body, tid1, tid2 = _otlp_traces_request()
        code, raw = http(server, "/v1/otlp/v1/traces", method="POST", body=body)
        assert code == 200, raw
        # services
        code, raw = http(server, "/v1/jaeger/api/services")
        assert "api-server" in json.loads(raw)["data"]
        # operations
        code, raw = http(server, "/v1/jaeger/api/operations?service=api-server")
        names = {o["name"] for o in json.loads(raw)["data"]}
        assert {"GET /api", "db.query", "GET /other"} <= names
        # get one trace
        code, raw = http(server, f"/v1/jaeger/api/traces/{tid1}")
        assert code == 200
        data = json.loads(raw)["data"]
        assert len(data) == 1 and len(data[0]["spans"]) == 2
        span = next(s for s in data[0]["spans"] if s["operationName"] == "GET /api")
        assert span["duration"] == 50_000
        child = next(s for s in data[0]["spans"] if s["operationName"] == "db.query")
        assert child["references"][0]["spanID"] == "0102030405060708"
        # search with filters
        q = urllib.parse.urlencode({"service": "api-server",
                                    "operation": "GET /other"})
        code, raw = http(server, f"/v1/jaeger/api/traces?{q}")
        data = json.loads(raw)["data"]
        assert [t["traceID"] for t in data] == [tid2]
        # min duration filter excludes the short trace
        q = urllib.parse.urlencode({"service": "api-server",
                                    "minDuration": "40000us"})
        code, raw = http(server, f"/v1/jaeger/api/traces?{q}")
        assert [t["traceID"] for t in json.loads(raw)["data"]] == [tid1]
        # unknown trace -> 404
        code, _ = http(server, "/v1/jaeger/api/traces/" + "00" * 16)
        assert code == 404
        # spans also queryable via plain SQL
        code, raw = http(server, "/v1/sql?" + urllib.parse.urlencode(
            {"sql": "SELECT count(*) FROM opentelemetry_traces"}))
        assert json.loads(raw)["output"][0]["records"]["rows"] == [[3]]

    def test_go_duration_units(self):
        from greptimedb_tpu.servers.http import _parse_go_duration_us

        assert _parse_go_duration_us("50us") == 50
        assert _parse_go_duration_us("100ms") == 100_000
        assert _parse_go_duration_us("2s") == 2_000_000
        assert _parse_go_duration_us("1m") == 60_000_000
        assert _parse_go_duration_us("250") == 250

    def test_multi_service_trace_processes(self):
        from greptimedb_tpu.servers.trace import _traces_payload

        spans = [
            {"service_name": "web", "trace_id": "t1", "span_id": "a",
             "parent_span_id": "", "span_name": "GET /", "span_kind":
             "SPAN_KIND_SERVER", "ts": 1, "duration_nano": 1000,
             "status_code": "STATUS_CODE_OK", "attributes": "{}"},
            {"service_name": "auth", "trace_id": "t1", "span_id": "b",
             "parent_span_id": "a", "span_name": "check", "span_kind":
             "SPAN_KIND_CLIENT", "ts": 2, "duration_nano": 500,
             "status_code": "STATUS_CODE_OK", "attributes": "{}"},
        ]
        out = _traces_payload({"t1": spans})
        procs = out[0]["processes"]
        by_op = {s["operationName"]: s["processID"] for s in out[0]["spans"]}
        assert procs[by_op["GET /"]]["serviceName"] == "web"
        assert procs[by_op["check"]]["serviceName"] == "auth"


class TestLogQueryApi:
    def test_log_query_dsl(self):
        db = GreptimeDB()
        srv = HttpServer(db, port=0)
        srv.start()
        try:
            payload = {"streams": [{
                "stream": {"app": "web"},
                "values": [
                    ["1700000000000000000", "GET /index ok"],
                    ["1700000001000000000", "error: boom"],
                    ["1700000002000000000", "GET /health ok"],
                ]}]}
            http(srv, "/v1/loki/api/v1/push", method="POST",
                 body=json.dumps(payload).encode(),
                 headers={"Content-Type": "application/json"})
            q = {
                "table": {"schema": "public", "table": "loki_logs"},
                "filters": [{"column": "line",
                             "filters": [{"contains": "error"}]}],
                "columns": ["ts", "line"],
                "limit": {"fetch": 10},
            }
            code, raw = http(srv, "/v1/logs", method="POST",
                             body=json.dumps(q).encode())
            assert code == 200, raw
            rec = json.loads(raw)["output"][0]["records"]
            assert rec["rows"] == [[1700000001000, "error: boom"]]
            # prefix + newest-first ordering + limit
            q2 = {"table": {"table": "loki_logs"},
                  "filters": [{"column": "line",
                               "filters": [{"prefix": "GET"}]}],
                  "columns": ["line"], "limit": {"fetch": 1}}
            code, raw = http(srv, "/v1/logs", method="POST",
                             body=json.dumps(q2).encode())
            rows = json.loads(raw)["output"][0]["records"]["rows"]
            assert rows == [["GET /health ok"]]
            # bad column -> 400
            q3 = {"table": {"table": "loki_logs"},
                  "filters": [{"column": "nope", "filters": [{"eq": "x"}]}]}
            code, _ = http(srv, "/v1/logs", method="POST",
                           body=json.dumps(q3).encode())
            assert code == 400
        finally:
            srv.stop()
            db.close()

    def test_log_query_empty_and_malformed(self):
        db = GreptimeDB()
        srv = HttpServer(db, port=0)
        srv.start()
        try:
            db.sql("CREATE TABLE el (app STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " line STRING, PRIMARY KEY (app))")
            # empty table + contains filter: zero rows, not a 500
            q = {"table": {"table": "el"},
                 "filters": [{"column": "line",
                              "filters": [{"contains": "x"}]}]}
            code, raw = http(srv, "/v1/logs", method="POST",
                             body=json.dumps(q).encode())
            assert code == 200, raw
            assert json.loads(raw)["output"][0]["records"]["rows"] == []
            # bad regex -> 400
            q["filters"][0]["filters"] = [{"regex": "("}]
            code, _ = http(srv, "/v1/logs", method="POST",
                           body=json.dumps(q).encode())
            assert code == 400
            # non-object body -> 400
            code, _ = http(srv, "/v1/logs", method="POST", body=b"[1, 2]")
            assert code == 400
        finally:
            srv.stop()
            db.close()



class TestDebugEndpoints:
    def test_dyn_log_level_and_prof(self):
        import json as _json
        import urllib.request

        from greptimedb_tpu.servers.http import HttpServer
        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB()
        srv = HttpServer(db, host="127.0.0.1", port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            out = _json.loads(urllib.request.urlopen(
                base + "/debug/log_level").read())
            assert "level" in out
            req = urllib.request.Request(
                base + "/debug/log_level", data=b"debug", method="POST")
            out = _json.loads(urllib.request.urlopen(req).read())
            assert out["level"] == "DEBUG"
            req = urllib.request.Request(
                base + "/debug/log_level", data=b"warning", method="POST")
            assert _json.loads(urllib.request.urlopen(req).read())[
                "level"] == "WARNING"
            prof = urllib.request.urlopen(
                base + "/debug/prof/cpu?seconds=0.3").read().decode()
            assert prof.startswith("samples=")
        finally:
            srv.stop()
            db.close()


class TestExternalTables:
    def test_external_parquet_and_csv(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from greptimedb_tpu.standalone import GreptimeDB

        t = pa.table({"host": ["a", "b", "a"],
                      "ts": pa.array([1000, 2000, 3000], pa.timestamp("ms")),
                      "v": [1.0, 2.0, 3.0]})
        pq.write_table(t, str(tmp_path / "p1.parquet"))
        (tmp_path / "c.csv").write_text("host,ts,v\na,1000,5.0\nc,4000,7.0\n")
        db = GreptimeDB()
        try:
            db.sql(f"CREATE EXTERNAL TABLE extp (host STRING, ts "
                   f"TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (host)) "
                   f"WITH (location='{tmp_path}/p1.parquet', "
                   f"format='parquet')")
            assert db.sql("SELECT host, sum(v) FROM extp GROUP BY host "
                          "ORDER BY host").rows == [["a", 4.0], ["b", 2.0]]
            db.sql(f"CREATE EXTERNAL TABLE extc (host STRING, ts "
                   f"TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (host)) "
                   f"WITH (location='{tmp_path}/c.csv', format='csv')")
            assert db.sql("SELECT count(*), max(v) FROM extc"
                          ).rows == [[2, 7.0]]
            from greptimedb_tpu.errors import Unsupported

            with pytest.raises(Unsupported):
                db.sql("INSERT INTO extp VALUES ('x', 9000, 1.0)")
            # joins between native and external tables work
            db.sql("CREATE TABLE nat (host STRING, ts TIMESTAMP(3) "
                   "TIME INDEX, w DOUBLE, PRIMARY KEY (host))")
            db.sql("INSERT INTO nat VALUES ('a', 0, 10.0)")
            r = db.sql("SELECT n.host, sum(e.v * n.w) FROM nat n "
                       "JOIN extp e ON n.host = e.host GROUP BY n.host")
            assert r.rows == [["a", 40.0]]
        finally:
            db.close()


class TestGcAndMetaSnapshot:
    def test_gc_deletes_orphans(self, tmp_path):
        import os
        import time

        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB(str(tmp_path / "home"))
        try:
            db.sql("CREATE TABLE g (h STRING, ts TIMESTAMP(3) TIME INDEX, "
                   "v DOUBLE, PRIMARY KEY (h))")
            db.sql("INSERT INTO g VALUES ('a', 1000, 1.0)")
            r = db._region_of("g")
            r.flush()
            rid = r.region_id
            # plant an orphan object (failed flush leftover)
            orphan = f"region_{rid}/sst/deadbeef.parquet"
            db.regions.store.write(orphan, b"junk")
            lp = db.regions.store.local_path(orphan)
            old = time.time() - 7200
            os.utime(lp, (old, old))
            deleted = db.regions.gc(grace_seconds=3600)
            assert orphan in deleted
            # live SSTs untouched
            assert db.sql("SELECT count(*) FROM g").rows == [[1]]
        finally:
            db.close()

    def test_meta_snapshot_restore(self, tmp_path):
        from greptimedb_tpu.cli import main as cli_main
        from greptimedb_tpu.standalone import GreptimeDB

        home = str(tmp_path / "home")
        db = GreptimeDB(home)
        db.sql("CREATE TABLE ms (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        db.close()
        snap = str(tmp_path / "meta.json")
        assert cli_main(["meta", "snapshot", "--data-home", home,
                         "--file", snap]) == 0
        home2 = str(tmp_path / "home2")
        assert cli_main(["meta", "restore", "--data-home", home2,
                         "--file", snap]) == 0
        db2 = GreptimeDB(home2)
        try:
            # table metadata restored (no data: that's export/import's job)
            assert db2.sql("SHOW TABLES").rows == [["ms"]]
        finally:
            db2.close()

    def test_recreated_external_table_not_stale(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from greptimedb_tpu.standalone import GreptimeDB

        pq.write_table(pa.table({"host": ["old"], "ts": pa.array(
            [1000], pa.timestamp("ms")), "v": [1.0]}),
            str(tmp_path / "a.parquet"))
        pq.write_table(pa.table({"host": ["new"], "ts": pa.array(
            [2000], pa.timestamp("ms")), "v": [2.0]}),
            str(tmp_path / "b.parquet"))
        db = GreptimeDB()
        try:
            ddl = ("CREATE EXTERNAL TABLE e (host STRING, ts TIMESTAMP(3) "
                   "TIME INDEX, v DOUBLE, PRIMARY KEY (host)) "
                   "WITH (location='{}', format='parquet')")
            db.sql(ddl.format(tmp_path / "a.parquet"))
            assert db.sql("SELECT host FROM e").rows == [["old"]]
            db.sql("DROP TABLE e")
            db.sql(ddl.format(tmp_path / "b.parquet"))
            assert db.sql("SELECT host FROM e").rows == [["new"]]
        finally:
            db.close()

    def test_join_star_hides_joinrow(self):
        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB()
        try:
            db.sql("CREATE TABLE a (h STRING, ts TIMESTAMP(3) TIME INDEX, "
                   "v DOUBLE, PRIMARY KEY (h))")
            db.sql("CREATE TABLE b (h STRING, ts TIMESTAMP(3) TIME INDEX, "
                   "w DOUBLE, PRIMARY KEY (h))")
            db.sql("INSERT INTO a VALUES ('x', 1000, 1.0)")
            db.sql("INSERT INTO b VALUES ('x', 2000, 2.0)")
            r = db.sql("SELECT * FROM a JOIN b ON a.h = b.h")
            assert "__joinrow__" not in r.column_names
        finally:
            db.close()


def _decode_read_response(raw: bytes) -> list[list[tuple[dict, list]]]:
    from greptimedb_tpu.servers.protocols import _pb_fields

    results = []
    for f, _wt, qr in _pb_fields(raw):
        if f != 1:
            continue
        series = []
        for f2, _wt2, ts_msg in _pb_fields(qr):
            if f2 != 1:
                continue
            labels, samples = {}, []
            for f3, _wt3, v3 in _pb_fields(ts_msg):
                if f3 == 1:
                    name = value = ""
                    for f4, _wt4, v4 in _pb_fields(v3):
                        if f4 == 1:
                            name = v4.decode()
                        elif f4 == 2:
                            value = v4.decode()
                    labels[name] = value
                elif f3 == 2:
                    val, ts = 0.0, 0
                    for f4, wt4, v4 in _pb_fields(v3):
                        if f4 == 1:
                            val = struct.unpack("<d", v4)[0]
                        elif f4 == 2:
                            ts = v4
                    samples.append((val, ts))
            series.append((labels, samples))
        results.append(series)
    return results


class TestPromRemoteRead:
    def test_write_then_remote_read(self, server):
        ts0 = 1700001000000
        pb = make_write_request([
            ({"__name__": "rr_metric", "job": "api", "inst": "a"},
             [(1.5, ts0), (2.5, ts0 + 10_000)]),
            ({"__name__": "rr_metric", "job": "web", "inst": "b"},
             [(9.0, ts0 + 5_000)]),
        ])
        code, _ = http(server, "/v1/prometheus/write", method="POST",
                       body=snappy.compress(pb),
                       headers={"Content-Encoding": "snappy"})
        assert code == 204
        # ReadRequest{queries=1:{start=1,end=2,matchers=3:{type=1,name=2,value=3}}}
        def matcher(mtype, name, value):
            m = b""
            if mtype:
                m += _pb_varint(1 << 3) + _pb_varint(mtype)
            m += _pb_len(2, name.encode()) + _pb_len(3, value.encode())
            return _pb_len(3, m)

        q = (_pb_varint(1 << 3) + _pb_varint(ts0 & ((1 << 64) - 1))
             + _pb_varint(2 << 3) + _pb_varint((ts0 + 60_000) & ((1 << 64) - 1))
             + matcher(0, "__name__", "rr_metric")
             + matcher(0, "job", "api"))
        req = _pb_len(1, q)
        code, raw = http(server, "/v1/prometheus/read", method="POST",
                         body=snappy.compress(req),
                         headers={"Content-Encoding": "snappy"})
        assert code == 200, raw
        results = _decode_read_response(snappy.decompress(raw))
        assert len(results) == 1
        series = results[0]
        assert len(series) == 1
        labels, samples = series[0]
        assert labels["__name__"] == "rr_metric"
        assert labels["job"] == "api" and labels["inst"] == "a"
        assert samples == [(1.5, ts0), (2.5, ts0 + 10_000)]

    def test_regex_matcher_and_missing_metric(self, server):
        def matcher(mtype, name, value):
            m = b""
            if mtype:
                m += _pb_varint(1 << 3) + _pb_varint(mtype)
            m += _pb_len(2, name.encode()) + _pb_len(3, value.encode())
            return _pb_len(3, m)

        ts0 = 1700001000000
        q = (_pb_varint(1 << 3) + _pb_varint(0)
             + _pb_varint(2 << 3) + _pb_varint((ts0 + 60_000))
             + matcher(0, "__name__", "rr_metric")
             + matcher(2, "job", "a.*|w.*"))
        code, raw = http(server, "/v1/prometheus/read", method="POST",
                         body=snappy.compress(_pb_len(1, q)),
                         headers={"Content-Encoding": "snappy"})
        assert code == 200
        got = _decode_read_response(snappy.decompress(raw))
        assert len(got[0]) == 2  # both series match the regex
        # unknown metric -> empty result, not an error
        q2 = (_pb_varint(1 << 3) + _pb_varint(0)
              + _pb_varint(2 << 3) + _pb_varint(ts0)
              + matcher(0, "__name__", "nope"))
        code, raw = http(server, "/v1/prometheus/read", method="POST",
                         body=snappy.compress(_pb_len(1, q2)),
                         headers={"Content-Encoding": "snappy"})
        assert code == 200
        assert _decode_read_response(snappy.decompress(raw)) == [[]]


def make_otlp_logs(records: list[dict]) -> bytes:
    """Build an ExportLogsServiceRequest from simple record dicts."""
    def any_str(s):
        return _pb_len(1, s.encode())

    def kv(k, v):
        return _pb_len(1, k.encode()) + _pb_len(2, any_str(v))

    recs = b""
    for r in records:
        body = b""
        body += _pb_varint((1 << 3) | 1) + struct.pack(
            "<Q", r["ts_ns"])  # time_unix_nano fixed64
        body += _pb_varint(2 << 3) + _pb_varint(r.get("severity_number", 9))
        body += _pb_len(3, r.get("severity_text", "INFO").encode())
        body += _pb_len(5, any_str(r["body"]))
        for k, v in r.get("attrs", {}).items():
            body += _pb_len(6, kv(k, v))
        if r.get("trace_id"):
            body += _pb_len(9, bytes.fromhex(r["trace_id"]))
        recs += _pb_len(2, body)
    scope = _pb_len(1, _pb_len(1, b"my-lib") + _pb_len(2, b"1.2.3"))
    scope_logs = _pb_len(2, scope + recs)
    resource = _pb_len(1, _pb_len(1, kv("service.name", "checkout")))
    return _pb_len(1, resource + scope_logs)


class TestOtlpLogs:
    def test_ingest_and_query(self, server):
        payload = make_otlp_logs([
            {"ts_ns": 1700000001000 * 10**6, "body": "user login ok",
             "attrs": {"user": "alice"}, "trace_id": "ab" * 16},
            {"ts_ns": 1700000002000 * 10**6, "body": "payment failed",
             "severity_text": "ERROR", "severity_number": 17},
        ])
        code, raw = http(server, "/v1/otlp/v1/logs", method="POST",
                         body=payload)
        assert code == 200, raw
        q = urllib.parse.urlencode({
            "sql": "SELECT severity_text, body, trace_id, "
                   "resource_attributes FROM opentelemetry_logs ORDER BY ts"})
        code, raw = http(server, f"/v1/sql?{q}")
        rows = json.loads(raw)["output"][0]["records"]["rows"]
        assert len(rows) == 2
        assert rows[0][1] == "user login ok" and rows[0][2] == "ab" * 16
        assert rows[1][0] == "ERROR"
        assert json.loads(rows[0][3]) == {"service.name": "checkout"}

    def test_custom_table_header(self, server):
        payload = make_otlp_logs([
            {"ts_ns": 1700000003000 * 10**6, "body": "x"}])
        code, _ = http(server, "/v1/otlp/v1/logs", method="POST",
                       body=payload,
                       headers={"x-greptime-log-table-name": "applogs"})
        assert code == 200
        q = urllib.parse.urlencode({"sql": "SELECT count(*) FROM applogs"})
        code, raw = http(server, f"/v1/sql?{q}")
        assert json.loads(raw)["output"][0]["records"]["rows"] == [[1]]
