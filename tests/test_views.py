"""CREATE VIEW / DROP VIEW / recycle-bin undrop (reference
src/common/meta/src/ddl/create_view.rs, purge_dropped_table.rs)."""

import pytest

from greptimedb_tpu.errors import (
    PlanError, TableAlreadyExists, TableNotFound,
)
from greptimedb_tpu.standalone import GreptimeDB


@pytest.fixture
def db(tmp_path):
    d = GreptimeDB(str(tmp_path / "v"))
    d.sql("CREATE TABLE cpu (host STRING, ts TIMESTAMP(3) TIME INDEX, "
          "u DOUBLE, PRIMARY KEY (host))")
    d.sql("INSERT INTO cpu VALUES " + ",".join(
        f"('h{i % 4}',{1700000000000 + i * 1000},{i % 7})"
        for i in range(400)))
    yield d
    d.close()


class TestViews:
    def test_view_over_aggregate(self, db):
        db.sql("CREATE VIEW busy AS SELECT host, date_trunc('minute', ts) "
               "AS m, avg(u) AS au FROM cpu GROUP BY host, m")
        r = db.sql("SELECT host, count(*) FROM busy GROUP BY host "
                   "ORDER BY host")
        assert [row[0] for row in r.rows] == ["h0", "h1", "h2", "h3"]
        # WHERE + projection over the view
        assert db.sql("SELECT count(*) FROM busy WHERE host = 'h2'"
                      ).rows[0][0] > 0

    def test_nested_views_and_replace(self, db):
        db.sql("CREATE VIEW v1 AS SELECT host, u FROM cpu WHERE u > 3")
        db.sql("CREATE VIEW v2 AS SELECT host, count(*) AS c FROM v1 "
               "GROUP BY host")
        assert db.sql("SELECT sum(c) FROM v2").rows[0][0] == \
            db.sql("SELECT count(*) FROM cpu WHERE u > 3").rows[0][0]
        db.sql("CREATE OR REPLACE VIEW v1 AS SELECT host, u FROM cpu "
               "WHERE u > 5")
        assert db.sql("SELECT sum(c) FROM v2").rows[0][0] == \
            db.sql("SELECT count(*) FROM cpu WHERE u > 5").rows[0][0]

    def test_view_survives_reopen(self, db, tmp_path):
        db.sql("CREATE VIEW vv AS SELECT host, u FROM cpu")
        home = db.data_home
        db.close()
        db2 = GreptimeDB(home)
        assert db2.sql("SELECT count(*) FROM vv").rows == [[400]]
        db2.close()

    def test_create_view_name_clash_and_drop(self, db):
        with pytest.raises(TableAlreadyExists):
            db.sql("CREATE VIEW cpu AS SELECT host, u FROM cpu")
        db.sql("CREATE VIEW dv AS SELECT host, u FROM cpu")
        with pytest.raises(Exception):
            db.sql("DROP VIEW cpu")  # cpu is a table, not a view
        db.sql("DROP VIEW dv")
        with pytest.raises(TableNotFound):
            db.sql("SELECT * FROM dv")
        db.sql("DROP VIEW IF EXISTS dv")  # idempotent

    def test_recursive_view_bounded(self, db):
        db.sql("CREATE VIEW r1 AS SELECT host, u FROM cpu")
        # redefine r1 in terms of itself via OR REPLACE
        db.sql("CREATE OR REPLACE VIEW r1 AS SELECT host, u FROM r1")
        with pytest.raises(PlanError):
            db.sql("SELECT count(*) FROM r1")


class TestRecycleBin:
    def test_drop_undrop_roundtrip(self, db):
        before = db.sql("SELECT count(*), sum(u) FROM cpu").rows
        db.sql("DROP TABLE cpu")
        with pytest.raises(TableNotFound):
            db.sql("SELECT count(*) FROM cpu")
        db.sql("ADMIN undrop_table('cpu')")
        assert db.sql("SELECT count(*), sum(u) FROM cpu").rows == before
        # inserts still work post-restore (WAL/seq state intact)
        db.sql("INSERT INTO cpu VALUES ('h9', 1700009999000, 1.0)")
        assert db.sql("SELECT count(*) FROM cpu").rows == [[401]]

    def test_undrop_survives_restart(self, db):
        db.sql("DROP TABLE cpu")
        home = db.data_home
        db.close()
        db2 = GreptimeDB(home)
        db2.sql("ADMIN undrop_table('cpu')")
        assert db2.sql("SELECT count(*) FROM cpu").rows == [[400]]
        db2.close()

    def test_undrop_blocked_by_recreation(self, db):
        db.sql("DROP TABLE cpu")
        db.sql("CREATE TABLE cpu (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        with pytest.raises(TableAlreadyExists):
            db.sql("ADMIN undrop_table('cpu')")

    def test_purge_deletes_data(self, db):
        db.sql("DROP TABLE cpu")
        rid_dirs = [p for p in db.regions.store.list("")
                    if p.startswith("region_")]
        assert rid_dirs  # data still on disk while recycled
        r = db.sql("ADMIN purge_recycle_bin()")
        assert "1" in r.rows[0][0]
        with pytest.raises(TableNotFound):
            db.sql("ADMIN undrop_table('cpu')")

    def test_purge_age_filter(self, db):
        db.sql("DROP TABLE cpu")
        r = db.sql("ADMIN purge_recycle_bin('7d')")  # too young to purge
        assert "0" in r.rows[0][0]
        db.sql("ADMIN undrop_table('cpu')")  # still restorable
        assert db.sql("SELECT count(*) FROM cpu").rows == [[400]]


def test_if_not_exists_and_join_guard(db):
    db.sql("CREATE VIEW IF NOT EXISTS ine AS SELECT host, u FROM cpu")
    db.sql("CREATE VIEW IF NOT EXISTS ine AS SELECT host FROM cpu")  # no-op
    assert db.sql("SELECT count(*) FROM ine").rows == [[400]]
    from greptimedb_tpu.errors import Unsupported

    with pytest.raises(Unsupported):
        db.sql("SELECT * FROM ine JOIN cpu ON ine.host = cpu.host")
    with pytest.raises(Unsupported):
        db.sql("SELECT * FROM cpu JOIN ine ON ine.host = cpu.host")


def test_drop_table_on_view_rejected(db):
    from greptimedb_tpu.errors import InvalidArguments

    db.sql("CREATE VIEW pv AS SELECT host, u FROM cpu")
    with pytest.raises(InvalidArguments):
        db.sql("DROP TABLE pv")
    assert db.sql("SELECT count(*) FROM pv").rows == [[400]]
