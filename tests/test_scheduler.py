"""Concurrent serving layer: scheduler, admission, batching, priorities.

Covers the PR's acceptance surface: bit-exact batched-vs-solo parity,
per-tenant quota rejection + fallback, priority ordering under a
saturated (background-occupied) pool, deadline shedding, and the
zero-overhead-disabled pin (GREPTIME_SCHEDULER=off ⇒ no serving
allocations on the warm path).

Reference counterpart: the frontend's admission/flow-control surface
(GreptimeDB limits concurrent queries per frontend and rejects with
RateLimited); the cross-query stacked dispatch is the Theseus
(arXiv 2508.05029) / Data Path Fusion (arXiv 2605.10511) move.
"""

import threading
import time

import numpy as np
import pytest

from greptimedb_tpu.errors import (
    Cancelled, DeadlineExceeded, RateLimited, ResourcesExhausted,
)
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.utils.telemetry import REGISTRY

pytestmark = pytest.mark.concurrency

T0 = 1451606400000  # TSBS epoch
HOSTS = 6
HOURS = 3
STEP_MS = 10_000


def _mk_db():
    db = GreptimeDB()
    db.sql(
        "CREATE TABLE cpu (hostname STRING, ts TIMESTAMP(3) TIME INDEX, "
        "usage_user DOUBLE, usage_system DOUBLE, PRIMARY KEY (hostname))"
    )
    rows = []
    rng = np.random.default_rng(7)
    vals = rng.uniform(0, 100, size=(HOSTS, HOURS * 360, 2))
    for h in range(HOSTS):
        for i in range(HOURS * 360):
            rows.append(
                f"('host_{h}', {T0 + i * STEP_MS}, "
                f"{vals[h, i, 0]:.3f}, {vals[h, i, 1]:.3f})"
            )
    for c in range(0, len(rows), 1000):
        db.sql("INSERT INTO cpu VALUES " + ",".join(rows[c:c + 1000]))
    return db


def _window_sql(hour_lo: int, hours: int = 1) -> str:
    lo = T0 + hour_lo * 3600_000
    hi = lo + hours * 3600_000
    return (
        "SELECT hostname, date_trunc('hour', ts) AS hour, "
        "avg(usage_user), avg(usage_system) FROM cpu "
        f"WHERE ts >= {lo} AND ts < {hi} GROUP BY hostname, hour"
    )


@pytest.fixture(scope="module")
def db():
    d = _mk_db()
    yield d
    d.close()


# ---------------------------------------------------------------------------
# Batched vs solo: bit-exact parity
# ---------------------------------------------------------------------------

class TestBatchParity:
    def test_stacked_dispatch_bit_exact(self, db):
        sched = db.scheduler
        assert sched is not None
        # warm the solo path (and the layout cache) per window class
        solo = {w: db.sql(_window_sql(w)) for w in range(HOURS)}
        b0 = REGISTRY.value("greptime_scheduler_batched_queries_total")
        results: dict[int, object] = {}
        errors: list = []

        def client(i):
            try:
                results[i] = sched.submit(_window_sql(i % HOURS))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        # repeat until at least one real multi-query dispatch happened —
        # closed-loop saturation forms batches, but a fast machine can
        # drain the queue before neighbors arrive
        for _ in range(20):
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            for i, res in results.items():
                want = solo[i % HOURS]
                assert res.column_names == want.column_names
                # BIT-exact: float cells compare with ==, not approx
                assert res.rows == want.rows
            if REGISTRY.value(
                    "greptime_scheduler_batched_queries_total") > b0:
                break
        assert REGISTRY.value(
            "greptime_scheduler_batched_queries_total") > b0, (
            "no stacked dispatch formed across 20 saturated rounds")
        assert db.scheduler.largest_batch > 1

    def test_tag_filtered_stacked_dispatch_bit_exact(self, db):
        """where_series extension: concurrent windows identical up to
        the tag filter (`hostname = 'host_i'`) coalesce into one stacked
        dispatch — each member's predicate rides in as a traced
        per-series mask — and every member's rows stay bit-exact vs its
        solo run."""
        sched = db.scheduler

        def q(i):
            lo = T0
            hi = lo + 3600_000
            return (
                "SELECT hostname, date_trunc('hour', ts) AS hour, "
                "avg(usage_user), avg(usage_system) FROM cpu "
                f"WHERE hostname = 'host_{i}' AND ts >= {lo} "
                f"AND ts < {hi} GROUP BY hostname, hour"
            )

        from greptimedb_tpu.query.physical import DISPATCH_STATS

        solo = {i: db.sql(q(i)) for i in range(HOSTS)}
        b0 = DISPATCH_STATS["grid_batch"]
        results: dict[int, object] = {}
        errors: list = []

        def client(i):
            try:
                results[i] = sched.submit(q(i % HOSTS))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        for _ in range(20):
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            for i, res in results.items():
                want = solo[i % HOSTS]
                assert res.column_names == want.column_names
                assert res.rows == want.rows  # BIT-exact
            if DISPATCH_STATS["grid_batch"] > b0:
                break
        assert DISPATCH_STATS["grid_batch"] > b0, (
            "no tag-filtered stacked dispatch formed in 20 rounds")

    def test_engine_batch_entry_bit_exact(self, db):
        """Direct engine-level parity: execute_select_batch vs
        execute_select on identical Selects, no scheduler timing luck."""
        from greptimedb_tpu.query.parser import parse_sql

        sels = [parse_sql(_window_sql(w))[0] for w in (0, 1, 2, 1)]
        solo = [db.engine.execute_select(s) for s in sels]
        batched = db.engine.execute_select_batch(sels)
        assert batched is not None
        for b, s in zip(batched, solo):
            assert b.column_names == s.column_names
            assert b.rows == s.rows

    def test_batch_falls_back_on_mixed_shapes(self, db):
        """Different window lengths (different bucket-count class) must
        refuse the stacked dispatch, not mis-batch."""
        from greptimedb_tpu.query.parser import parse_sql

        sels = [parse_sql(_window_sql(0, 1))[0],
                parse_sql(_window_sql(0, 2))[0]]
        assert db.engine.execute_select_batch(sels) is None

    def test_batch_refuses_views_and_system_tables(self, db):
        from greptimedb_tpu.query.parser import parse_sql

        s = parse_sql("SELECT table_name FROM information_schema.tables")[0]
        assert db.sql_batch([("q", s, None, None),
                             ("q", s, None, None)]) is None


# ---------------------------------------------------------------------------
# Per-tenant admission
# ---------------------------------------------------------------------------

class TestTenantAdmission:
    def test_rate_quota_rejects_then_refills(self, db):
        sched = db.scheduler
        sched.admission.set_quota("rate_t", qps=20.0, burst=2)
        assert sched.submit("SELECT 1", tenant="rate_t").rows == [[1]]
        with pytest.raises(RateLimited) as ei:
            for _ in range(8):  # burst is 2; the loop must trip the limit
                sched.submit("SELECT 1", tenant="rate_t")
        assert "over rate quota" in str(ei.value)
        # fallback: tokens refill (20 qps), the tenant recovers
        time.sleep(0.15)
        assert sched.submit("SELECT 1", tenant="rate_t").rows == [[1]]
        assert REGISTRY.value("greptime_scheduler_rejected_total",
                              ("rate_t", "rate")) >= 1

    def test_memory_quota_rejects_via_workload_manager(self, db):
        sched = db.scheduler
        sched.admission.set_quota(
            "mem_t", mem_bytes=sched.query_est_bytes // 2)
        with pytest.raises(ResourcesExhausted) as ei:
            sched.submit("SELECT 1", tenant="mem_t")
        assert "over memory quota" in str(ei.value)
        # the budget registered as a first-class workload: same pull
        # gauges and usage surface as every other workload
        usage = db.memory.usage()
        assert "tenant:mem_t" in usage
        assert usage["tenant:mem_t"]["rejected"] >= 1
        # fallback: lift the quota, the tenant is served again
        sched.admission.set_quota("mem_t", mem_bytes=None)
        assert sched.submit("SELECT 1", tenant="mem_t").rows == [[1]]

    def test_concurrency_quota_and_try_admit_fallback(self, db):
        sched = db.scheduler
        sched.admission.set_quota("cc_t", max_inflight=1)
        sched.admission.admit("cc_t")  # occupy the only slot
        try:
            with pytest.raises(RateLimited):
                sched.admission.admit("cc_t")
            assert sched.admission.try_admit("cc_t") is False
        finally:
            sched.admission.release("cc_t")
        assert sched.admission.try_admit("cc_t") is True
        sched.admission.release("cc_t")

    def test_queue_full_backpressure(self, db):
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1, max_queue=1, batching=False)
        release = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            release.wait(5)
            return "done"

        t = threading.Thread(
            target=lambda: s.submit_fn(blocker, priority="background"))
        t.start()
        started.wait(5)
        # worker busy; one entry fills the queue, the next is rejected
        t2 = threading.Thread(
            target=lambda: s.submit_fn(lambda: None,
                                       priority="background"))
        t2.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with s._cond:
                if sum(len(q) for q in s._queues.values()) >= 1:
                    break
            time.sleep(0.005)
        with pytest.raises(ResourcesExhausted) as ei:
            s.submit_fn(lambda: None, priority="background")
        assert "queue full" in str(ei.value)
        release.set()
        t.join(5)
        t2.join(5)
        s.stop()


# ---------------------------------------------------------------------------
# Priorities + scan-pool preemption
# ---------------------------------------------------------------------------

class TestPriorities:
    def test_interactive_overtakes_background_queue(self, db):
        """One worker, occupied: later-submitted interactive work must
        complete before earlier-queued background work."""
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1, batching=False)
        order: list[str] = []
        release = threading.Event()
        started = threading.Event()

        def occupy():
            started.set()
            release.wait(5)

        threads = [threading.Thread(
            target=lambda: s.submit_fn(occupy, priority="background"))]
        threads[0].start()
        started.wait(5)

        def bg():
            order.append("background")

        def ia():
            order.append("interactive")

        for fn, prio in ((bg, "background"), (bg, "background"),
                         (ia, "interactive")):
            threads.append(threading.Thread(
                target=lambda f=fn, p=prio: s.submit_fn(f, priority=p)))
            threads[-1].start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with s._cond:
                if sum(len(q) for q in s._queues.values()) == 3:
                    break
            time.sleep(0.005)
        release.set()
        for t in threads:
            t.join(5)
        assert order[0] == "interactive", order
        s.stop()

    def test_scan_pool_yields_to_interactive(self, db):
        """A background-priority thread narrows the cold-scan decode pool
        to 1 while interactive queries wait (cooperative preemption)."""
        from greptimedb_tpu.serving import scheduler as sched_mod
        from greptimedb_tpu.storage.scan import scan_threads

        assert scan_threads(8) >= 1
        sched_mod._worker_local.priority = "background"
        try:
            with sched_mod._wait_lock:
                sched_mod._interactive_waiting += 1
            try:
                assert sched_mod.background_should_yield() is True
                assert scan_threads(8) == 1
            finally:
                with sched_mod._wait_lock:
                    sched_mod._interactive_waiting -= 1
            assert sched_mod.background_should_yield() is False
            assert scan_threads(8) >= 1
        finally:
            sched_mod._worker_local.priority = None

    def test_statement_classification(self, db):
        from greptimedb_tpu.query.parser import parse_sql

        s = db.scheduler
        assert s.classify(parse_sql("SELECT 1")) == "interactive"
        assert s.classify(parse_sql("INSERT INTO cpu VALUES "
                                    "('x', 1, 1.0, 1.0)")) == "normal"
        assert s.classify(parse_sql(
            "COPY cpu TO '/tmp/x.parquet'")) == "background"
        assert s.classify(parse_sql("ADMIN flush_table('cpu')")) == (
            "background")


# ---------------------------------------------------------------------------
# Deadline shedding
# ---------------------------------------------------------------------------

class TestAdaptiveLinger:
    """Round-13 satellite: the group-commit linger scales with observed
    same-class pressure instead of firing at a constant — idle traffic
    must never pay it."""

    def test_effective_linger_scales_with_pressure(self, db):
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1)
        s.linger_ms = 100.0
        ceiling = 0.1
        # idle: nothing else in flight -> zero linger
        s._sqlish_inflight["interactive"] = 1
        assert s._effective_linger_s("interactive", 1) == 0.0
        # light contention: a fraction of the ceiling
        s._sqlish_inflight["interactive"] = 1 + s.max_batch // 2
        mid = s._effective_linger_s("interactive", 1)
        assert 0.0 < mid < ceiling
        # saturation (a full batch's worth pending): the whole ceiling
        s._sqlish_inflight["interactive"] = 1 + s.max_batch
        assert s._effective_linger_s("interactive", 1) == ceiling
        # other priority classes don't bleed into the signal
        assert s._effective_linger_s("background", 1) == 0.0
        s._sqlish_inflight["interactive"] = 0
        s.stop()

    def test_idle_path_p50_pays_no_linger(self, db):
        """A lone sequential client must not wait out the linger window:
        with a deliberately huge ceiling (250 ms), 9 solo submits whose
        p50 stays far under it prove the idle path dispatches
        immediately."""
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1)
        s.linger_ms = 250.0
        try:
            s.submit(_window_sql(0))  # warm compile/layout outside timing
            lat_ms = []
            for _ in range(9):
                t0 = time.perf_counter()
                s.submit(_window_sql(0))
                lat_ms.append((time.perf_counter() - t0) * 1000)
            p50 = sorted(lat_ms)[len(lat_ms) // 2]
            assert p50 < 250.0, (
                f"idle p50 {p50:.1f} ms >= linger ceiling — idle traffic "
                f"is paying the group-commit linger")
        finally:
            s.stop()


class TestDeadlines:
    def test_queued_entry_sheds_at_deadline(self, db):
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1, batching=False)
        release = threading.Event()
        started = threading.Event()

        def occupy():
            started.set()
            release.wait(5)

        t = threading.Thread(
            target=lambda: s.submit_fn(occupy, priority="background"))
        t.start()
        started.wait(5)
        shed0 = REGISTRY.value("greptime_scheduler_shed_total",
                               ("interactive",))
        err: list = []

        def victim():
            try:
                s.submit("SELECT 1", timeout_s=0.05)
            except Exception as e:  # noqa: BLE001
                err.append(e)

        v = threading.Thread(target=victim)
        v.start()
        time.sleep(0.2)  # deadline passes while queued
        release.set()
        v.join(5)
        t.join(5)
        assert err and isinstance(err[0], DeadlineExceeded), err
        assert REGISTRY.value("greptime_scheduler_shed_total",
                              ("interactive",)) > shed0
        s.stop()

    def test_stop_cancels_queued(self, db):
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1, batching=False)
        release = threading.Event()
        started = threading.Event()
        errs: list = []

        def occupy():
            started.set()
            release.wait(5)

        t = threading.Thread(
            target=lambda: s.submit_fn(occupy, priority="background"))
        t.start()
        started.wait(5)

        def queued():
            try:
                s.submit("SELECT 1")
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        q = threading.Thread(target=queued)
        q.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with s._cond:
                if s._queues["interactive"]:
                    break
            time.sleep(0.005)
        release.set()
        s.stop()
        q.join(5)
        t.join(5)
        assert errs and isinstance(errs[0], Cancelled)


# ---------------------------------------------------------------------------
# Observability surface
# ---------------------------------------------------------------------------

class TestObservability:
    def test_explain_analyze_scheduler_row(self, db):
        r = db.scheduler.submit("EXPLAIN ANALYZE " + _window_sql(0))
        labels = [row[0] for row in r.rows]
        assert "analyze (scheduler)" in labels
        body = r.rows[labels.index("analyze (scheduler)")][1]
        assert "wait_ms" in body and "queue_depth" in body
        # the analyze metric lines carry the scheduler columns too
        analyze = r.rows[labels.index("analyze (cold vs warm ms)")][1]
        assert "sched_wait_ms" in analyze
        assert "sched_batch" in analyze

    def test_direct_sql_explain_analyze_format_unpolluted(self, db):
        """The pinned seed format: EXPLAIN ANALYZE issued directly (not
        through the scheduler) shows no scheduler rows or keys."""
        r = db.sql("EXPLAIN ANALYZE " + _window_sql(0))
        labels = [row[0] for row in r.rows]
        assert "analyze (scheduler)" not in labels
        assert "sched_wait_ms" not in r.rows[1][1]

    def test_slow_queries_scheduler_columns(self, db):
        prev = db.slow_query_threshold_ms
        db.slow_query_threshold_ms = 0.0001
        try:
            db.scheduler.submit(_window_sql(1))
        finally:
            db.slow_query_threshold_ms = prev
        r = db.sql("SELECT query, sched_wait_ms, sched_batch FROM "
                   "greptime_private.slow_queries ORDER BY ts DESC LIMIT 5")
        assert r.rows, "slow query not recorded"
        target = [row for row in r.rows if "avg(usage_user)" in row[0]]
        assert target, r.rows
        assert target[0][1] >= 0.0  # sched_wait_ms recorded
        assert target[0][2] >= 1.0  # sched_batch recorded

    def test_queue_depth_gauge_and_wait_histogram(self, db):
        db.scheduler.submit("SELECT 1")
        text = REGISTRY.render()
        assert 'greptime_scheduler_queue_depth{priority="interactive"}' in text
        assert REGISTRY.value("greptime_scheduler_wait_seconds",
                              ("interactive",)) > 0

    def test_scheduler_span_in_trace(self, db):
        from greptimedb_tpu.utils.tracing import TRACER

        try:
            TRACER.configure()
            mark = TRACER.mark()
            db.scheduler.submit("SELECT 1")
            spans = TRACER.since(mark)
            assert any(s["name"] == "scheduler" for s in spans), (
                [s["name"] for s in spans])
            sched_span = next(s for s in spans if s["name"] == "scheduler")
            assert "wait_ms" in sched_span.get("attributes", {})
        finally:
            TRACER.disable()

    def test_processlist_sees_queued_entries(self, db):
        """Entries register in the process registry at submit: SHOW
        PROCESSLIST shows them even while queued behind a busy worker."""
        from greptimedb_tpu.serving.scheduler import QueryScheduler

        s = QueryScheduler(db, workers=1, batching=False)
        release = threading.Event()
        started = threading.Event()

        def occupy():
            started.set()
            release.wait(5)

        t = threading.Thread(
            target=lambda: s.submit_fn(occupy, priority="background"))
        t.start()
        started.wait(5)
        marker = "SELECT 424242"
        q = threading.Thread(target=lambda: s.submit(marker))
        q.start()
        deadline = time.time() + 5
        seen = False
        while time.time() < deadline and not seen:
            rows = db.sql("SHOW PROCESSLIST").rows
            seen = any(marker in r[3] for r in rows)
            time.sleep(0.005)
        release.set()
        q.join(5)
        t.join(5)
        s.stop()
        assert seen, "queued entry never appeared in SHOW PROCESSLIST"


# ---------------------------------------------------------------------------
# Zero-overhead disabled
# ---------------------------------------------------------------------------

class TestDisabled:
    def test_scheduler_off_restores_inline_path(self, monkeypatch):
        monkeypatch.setenv("GREPTIME_SCHEDULER", "off")
        d = GreptimeDB()
        try:
            assert d.scheduler is None
            d.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
                  "v DOUBLE, PRIMARY KEY (h))")
            d.sql("INSERT INTO t VALUES ('a', 1000, 1.0)")
            warm_sql = "SELECT h, avg(v) FROM t GROUP BY h"
            d.sql(warm_sql)  # warm
            # no new allocations from serving/ on the warm path: trace
            # allocations of a warm query and assert none originate in
            # the serving package
            import tracemalloc

            tracemalloc.start()
            d.sql(warm_sql)
            snap = tracemalloc.take_snapshot()
            tracemalloc.stop()
            serving_allocs = [
                st for st in snap.statistics("filename")
                if "/serving/" in st.traceback[0].filename
            ]
            assert serving_allocs == [], serving_allocs
        finally:
            d.close()

    def test_scheduler_off_server_calls_inline(self, monkeypatch):
        """HTTP server with scheduler off keeps the single-worker inline
        executor path (no submit pool is ever created)."""
        monkeypatch.setenv("GREPTIME_SCHEDULER", "off")
        from greptimedb_tpu.servers import HttpServer

        d = GreptimeDB()
        srv = HttpServer(d, port=0)
        try:
            srv.start()
            import json
            import urllib.request

            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/sql?sql=SELECT+1",
                timeout=10,
            ) as resp:
                body = json.load(resp)
            assert body["output"][0]["records"]["rows"] == [[1]]
            assert srv._submit_pool is None
        finally:
            srv.stop()
            d.close()

    def test_off_knob_keeps_metrics_silent(self, monkeypatch):
        monkeypatch.setenv("GREPTIME_SCHEDULER", "off")
        d = GreptimeDB()
        try:
            before = REGISTRY.value("greptime_scheduler_executed_total",
                                    ("interactive",))
            d.sql("SELECT 1")
            assert REGISTRY.value("greptime_scheduler_executed_total",
                                  ("interactive",)) == before
        finally:
            d.close()


# ---------------------------------------------------------------------------
# HTTP integration: tenant header + 429 surface
# ---------------------------------------------------------------------------

class TestHttpIntegration:
    def test_http_tenant_quota_429(self):
        import json
        import urllib.error
        import urllib.request

        from greptimedb_tpu.servers import HttpServer

        d = GreptimeDB()
        srv = HttpServer(d, port=0)
        try:
            srv.start()
            assert d.scheduler is not None
            # qps low enough that the closed HTTP round-trip can never
            # refill a whole token between calls
            d.scheduler.admission.set_quota("limited", qps=0.5, burst=1)

            def call():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/v1/sql?sql=SELECT+1",
                    headers={"x-greptime-tenant": "limited"})
                return urllib.request.urlopen(req, timeout=10)

            with call() as resp:
                assert json.load(resp)["output"][0]["records"][
                    "rows"] == [[1]]
            codes = []
            for _ in range(6):
                try:
                    with call() as resp:
                        codes.append(resp.status)
                except urllib.error.HTTPError as e:
                    codes.append(e.code)
                    body = json.load(e)
                    assert "over rate quota" in body["error"]
            assert 429 in codes, codes
        finally:
            srv.stop()
            d.close()
