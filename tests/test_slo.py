"""Closed-loop SLO observatory (ISSUE 18 tentpole).

Pins: sketch quantile accuracy vs numpy under fuzzed distributions and
merge equivalence (the DDSketch contract); burn-rate window goldens on
an injected clock — alerts fire during an induced storm and CLEAR once
it passes; the idle economy's fairness invariants (weighted time split,
greedy cannot starve the meek, the starvation bound guarantees
liveness); exactly-one SLO accounting per scheduler entry including
errors, sheds and caller-held (http) samples; and the
``GREPTIME_SLO=off`` zero-overhead pin (module never imported, legacy
idle dispatcher byte-for-byte).
"""

import math
import subprocess
import sys

import numpy as np
import pytest

from greptimedb_tpu.serving.idle import IdleEconomy
from greptimedb_tpu.serving.slo import (
    LatencySketch, SloEngine, _MIN_S, sketch_params,
)

ALPHA = 0.01
PARAMS = sketch_params(ALPHA)


def _rank_quantile(vals, q):
    """The rank-based sample quantile the sketch estimates (DDSketch
    guarantees relative error alpha against THIS, not interpolation)."""
    s = np.sort(vals)
    return float(s[max(1, math.ceil(q * len(s))) - 1])


class TestSketchAccuracy:
    DISTS = (
        ("lognormal", lambda r, n: r.lognormal(-3.0, 1.0, n)),
        ("uniform", lambda r, n: r.uniform(0.001, 2.0, n)),
        ("exponential", lambda r, n: r.exponential(0.05, n)),
    )

    def test_quantiles_within_relative_error_fuzzed(self):
        for seed in (7, 21, 99):
            rng = np.random.default_rng(seed)
            for name, gen in self.DISTS:
                vals = np.clip(gen(rng, 5000), 2e-4, 5e3)
                sk = LatencySketch(PARAMS)
                for v in vals:
                    sk.observe(float(v))
                assert sk.n == 5000
                for q in (0.50, 0.90, 0.99, 0.999):
                    est = sk.quantile(q)
                    true = _rank_quantile(vals, q)
                    rel = abs(est - true) / true
                    assert rel <= 2 * ALPHA, (name, seed, q, est, true)

    def test_merge_equals_observing_everything(self):
        rng = np.random.default_rng(13)
        vals = np.clip(rng.lognormal(-2.5, 1.2, 3000), 2e-4, 5e3)
        whole = LatencySketch(PARAMS)
        parts = [LatencySketch(PARAMS) for _ in range(3)]
        for i, v in enumerate(vals):
            whole.observe(float(v))
            parts[i % 3].observe(float(v))
        merged = LatencySketch(PARAMS)
        for p in parts:
            merged.merge(p)
        assert merged.counts == whole.counts
        assert merged.n == whole.n
        assert merged.sum == pytest.approx(whole.sum)
        for q in (0.5, 0.99):
            assert merged.quantile(q) == whole.quantile(q)

    def test_range_clamps_never_raise(self):
        sk = LatencySketch(PARAMS)
        sk.observe(0.0)        # sub-minimum → bucket 0
        sk.observe(1e-9)
        sk.observe(1e9)        # absurd → top bucket, no index error
        assert sk.n == 3
        assert sk.quantile(0.0) == _MIN_S
        assert sk.quantile(1.0) >= 1e3

    def test_empty_sketch_has_no_quantile(self):
        assert LatencySketch(PARAMS).quantile(0.5) is None


def _engine(monkeypatch, **env):
    """SloEngine on an injected, manually-advanced clock."""
    defaults = {
        "GREPTIME_SLO_MIN_SAMPLES": "10",
        "GREPTIME_SLO_OBJECTIVE": "0.999",
        "GREPTIME_SLO_THRESHOLD_MS": "500",
    }
    defaults.update(env)
    for k, v in defaults.items():
        monkeypatch.setenv(k, str(v))
    t = [10_000.0]
    eng = SloEngine(clock=lambda: t[0])
    return eng, t


class TestBurnWindows:
    KEY = ("default", "interactive", "http")

    def _record(self, eng, n, bad=0, seconds=0.01):
        for _ in range(n - bad):
            eng.record(*self.KEY, seconds)
        for _ in range(bad):
            eng.record(*self.KEY, 10.0)  # >> threshold: breach

    def test_goldens(self, monkeypatch):
        eng, t = _engine(monkeypatch)
        # no traffic: burn 0, full budget
        assert eng.burn_rate(self.KEY, "5m") == 0.0
        assert eng.budget_remaining(self.KEY) == 1.0
        # 1000 clean: still no burn
        self._record(eng, 1000)
        assert eng.burn_rate(self.KEY, "5m") == 0.0
        assert eng.budget_remaining(self.KEY) == 1.0
        # 5 breaches in 1005: ratio .004975 over budget .001 → burn ~4.98
        self._record(eng, 5, bad=5)
        for w in ("5m", "30m", "1h", "6h"):
            assert eng.burn_rate(self.KEY, w) == pytest.approx(
                (5 / 1005) / 0.001, rel=1e-6), w
        assert eng.budget_remaining(self.KEY) == pytest.approx(
            max(0.0, 1.0 - (5 / 1005) / 0.001))

    def test_short_window_forgets_the_storm(self, monkeypatch):
        eng, t = _engine(monkeypatch)
        self._record(eng, 100, bad=50)
        assert eng.burn_rate(self.KEY, "5m") > 0
        t[0] += 6 * 60.0  # 6 slots later: outside 5m, inside 1h
        assert eng.burn_rate(self.KEY, "5m") == 0.0
        assert eng.burn_rate(self.KEY, "1h") > 0
        t[0] += 60 * 60.0  # and eventually outside 1h, inside 6h
        assert eng.burn_rate(self.KEY, "1h") == 0.0
        assert eng.burn_rate(self.KEY, "6h") > 0

    def test_alert_fires_during_storm_and_clears(self, monkeypatch):
        eng, t = _engine(monkeypatch)
        # storm: 5% breaches → burn 50 >> fast threshold 14.4 on BOTH
        # fast-pair windows, with ample samples
        self._record(eng, 600, bad=30)
        alerts = eng.alerts()
        severities = {a["severity"] for a in alerts}
        assert "fast" in severities
        assert eng.fast_burn_active()
        # storm passes: clean traffic refills the short window; the fast
        # pair needs the short window STILL burning, so it clears even
        # though the 1h window remembers the storm
        t[0] += 6 * 60.0
        self._record(eng, 600)
        t[0] += 2.0  # invalidate the 1s alert cache
        assert eng.burn_rate(self.KEY, "1h") > 14.4
        assert not eng.fast_burn_active()

    def test_min_samples_gates_thin_traffic(self, monkeypatch):
        eng, t = _engine(monkeypatch)
        # 5 queries, ALL breaches — a 3am test database, not a storm
        self._record(eng, 5, bad=5)
        assert eng.burn_rate(self.KEY, "5m") > 900  # ratio says burning
        assert eng.alerts() == []                    # evidence says no
        assert not eng.fast_burn_active()

    def test_tenant_overrides_and_class_factors(self, monkeypatch):
        eng, _t = _engine(
            monkeypatch, GREPTIME_SLO_OVERRIDES="acme=250:0.99, bad==,x")
        assert eng.objective_for("acme", "interactive") == (0.25, 0.99)
        assert eng.objective_for("acme", "background") == (
            pytest.approx(5.0), 0.99)
        assert eng.objective_for("other", "interactive") == (0.5, 0.999)
        # runtime override (the soak's induced storm)
        eng.set_objective("other", 1.0)
        thr, obj = eng.objective_for("other", "interactive")
        assert thr == pytest.approx(0.001) and obj == 0.999

    def test_adaptive_timeout_needs_evidence(self, monkeypatch):
        eng, _t = _engine(monkeypatch)
        assert eng.adaptive_timeout_s("interactive") is None
        for _ in range(300):
            eng.record("default", "interactive", "http", 0.05)
        # p99 ~50ms × 8 « floor → the generous floor wins
        assert eng.adaptive_timeout_s("interactive") == 30.0
        for _ in range(300):
            eng.record("default", "normal", "http", 10.0)
        # p99 ~10s × 8 = 80s > floor
        assert eng.adaptive_timeout_s("normal") == pytest.approx(
            80.0, rel=0.05)

    def test_admit_background_scales_with_budget(self, monkeypatch):
        eng, _t = _engine(monkeypatch, GREPTIME_SLO_ADMIT_MS="60000")
        # full budget: the whole allowance
        ok, allowance = eng.admit_background(50_000)
        assert ok and allowance == 60_000
        # burned-out interactive budget: allowance collapses; unknown
        # (0-cost) work is still admitted
        self._record(eng, 100, bad=50)
        ok, allowance = eng.admit_background(50_000)
        assert not ok and allowance == 0.0
        assert eng.admit_background(0)[0]

    def test_status_rows_render_every_key(self, monkeypatch):
        eng, _t = _engine(monkeypatch)
        eng.record("a", "interactive", "http", 0.01)
        eng.record("b", "background", "sql", 2.0)
        rows = eng.status_rows()
        assert [(r["tenant"], r["class"]) for r in rows] == [
            ("a", "interactive"), ("b", "background")]
        assert rows[0]["total"] == 1 and rows[0]["breached"] == 0
        assert rows[1]["p50_ms"] == pytest.approx(2000.0, rel=2 * ALPHA)
        assert eng.total_recorded() == 2


class TestIdleEconomy:
    def _eco(self, monkeypatch, t, **env):
        defaults = {"GREPTIME_IDLE_QUANTUM_MS": "20",
                    "GREPTIME_IDLE_STARVE_TICKS": "64"}
        defaults.update(env)
        for k, v in defaults.items():
            monkeypatch.setenv(k, str(v))
        return IdleEconomy(clock=lambda: t[0])

    def test_weighted_time_split_deterministic(self, monkeypatch):
        t = [0.0]
        eco = self._eco(monkeypatch, t)
        ledger = {"a": 0.040, "b": 0.020}  # simulated tick durations

        def consumer(name):
            def fn():
                t[0] += ledger[name]
                return True
            return fn

        eco.register(consumer("a"), name="a", weight=2.0)
        eco.register(consumer("b"), name="b", weight=1.0)
        for _ in range(60):
            assert eco.tick() is True
        by = {c["name"]: c for c in eco.consumers()}
        # deterministic DRR schedule (a,a,b repeating): grants follow
        # the 2:1 weights exactly because each grant of a costs its
        # weight in quanta (40 ms / 20 ms quantum = 2)
        assert by["a"]["granted"] == 40 and by["b"]["granted"] == 20
        assert by["a"]["elapsed_ms"] == pytest.approx(4 * by["b"]["elapsed_ms"])
        assert by["a"]["starved"] == 0 and by["b"]["starved"] == 0

    def test_greedy_cannot_starve_the_meek(self, monkeypatch):
        t = [0.0]
        eco = self._eco(monkeypatch, t)

        def greedy():
            t[0] += 1.0  # 50 quanta per tick
            return True

        def meek():
            t[0] += 0.001
            return True

        eco.register(greedy, name="greedy", weight=1.0)
        eco.register(meek, name="meek", weight=1.0)
        for _ in range(80):
            eco.tick()
        by = {c["name"]: c for c in eco.consumers()}
        # the deficit debit makes every greedy grant cost ~50 future
        # grants: the meek consumer runs far more often, no starvation
        # bound needed
        assert by["meek"]["granted"] > 5 * by["greedy"]["granted"]
        assert by["meek"]["starved"] == 0

    def test_starvation_bound_guarantees_liveness(self, monkeypatch):
        from greptimedb_tpu.utils.telemetry import REGISTRY

        t = [0.0]
        eco = self._eco(monkeypatch, t, GREPTIME_IDLE_STARVE_TICKS="5")

        def fn():
            return True

        eco.register(fn, name="rich", weight=1.0)
        eco.register(lambda: True, name="zero", weight=0.0)
        for _ in range(20):
            eco.tick()
        by = {c["name"]: c for c in eco.consumers()}
        # weight 0 accrues nothing — only the bound ever grants it
        assert by["zero"]["granted"] >= 2
        assert by["zero"]["starved"] == by["zero"]["granted"]
        assert (REGISTRY.value("greptime_idle_starved_total",
                               ("zero",)) or 0) >= 2

    def test_drain_unhook_and_resurrect(self, monkeypatch):
        t = [0.0]
        eco = self._eco(monkeypatch, t)
        calls = []

        def once():
            calls.append(1)
            return False  # drained after one grant

        name = eco.register(once, name="once")
        assert eco.tick() is False  # all drained → unhook contract
        assert len(calls) == 1
        # re-registering the SAME callable revives the ledger entry
        assert eco.register(once) == name
        assert [c["name"] for c in eco.consumers()] == [name]
        assert eco.tick() is False
        assert len(calls) == 2

    def test_fast_burn_throttles_every_consumer(self, monkeypatch):
        t = [0.0]

        class FakeSlo:
            burning = True

            def fast_burn_active(self):
                return self.burning

        slo = FakeSlo()
        for k, v in (("GREPTIME_IDLE_QUANTUM_MS", "20"),
                     ("GREPTIME_IDLE_STARVE_TICKS", "64")):
            monkeypatch.setenv(k, v)
        eco = IdleEconomy(slo=slo, clock=lambda: t[0])
        granted = []
        eco.register(lambda: granted.append(1) or True, name="w")
        for _ in range(5):
            assert eco.tick() is True  # stays hooked, grants NOTHING
        assert granted == [] and eco.throttled == 5
        slo.burning = False
        eco.tick()
        assert granted == [1]

    def test_exceptions_drain_not_kill(self, monkeypatch):
        t = [0.0]
        eco = self._eco(monkeypatch, t)

        def boom():
            raise RuntimeError("consumer bug")

        eco.register(boom, name="boom")
        eco.register(lambda: True, name="ok")
        assert eco.tick() in (True, False)
        assert eco.tick() is True  # 'ok' still lives
        by = {c["name"]: c for c in eco.consumers()}
        assert by["boom"]["drained"]


class TestSchedulerAccounting:
    """Exactly-one sketch sample per scheduler entry — success, error,
    shed and caller-held paths."""

    @pytest.fixture()
    def db(self):
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB()
        d.sql("CREATE TABLE cpu (h STRING, ts TIMESTAMP TIME INDEX, "
              "v DOUBLE, PRIMARY KEY(h))")
        d.sql("INSERT INTO cpu VALUES ('a', 1000, 1.0), ('a', 2000, 2.0)")
        yield d
        d.close()

    def test_every_submit_lands_in_exactly_one_sketch(self, db):
        if db.scheduler is None or db.slo is None:
            pytest.skip("scheduler/slo disabled in this config")
        base = db.slo.total_recorded()
        n_ok, n_err = 12, 3
        for i in range(n_ok):
            db.scheduler.submit(f"SELECT count(v) FROM cpu WHERE v > {i}")
        for _ in range(n_err):
            with pytest.raises(Exception):
                db.scheduler.submit("SELECT definitely_no_such_col "
                                    "FROM cpu")
        assert db.slo.total_recorded() == base + n_ok + n_err

    def test_held_sample_defers_to_the_caller(self, db):
        if db.scheduler is None or db.slo is None:
            pytest.skip("scheduler/slo disabled in this config")
        base = db.slo.total_recorded()
        hold = []
        db.scheduler.submit("SELECT count(v) FROM cpu", slo_hold=hold)
        # not yet recorded: serialization is still ahead
        assert db.slo.total_recorded() == base
        assert len(hold) == 1
        db.scheduler.record_held(hold)
        assert db.slo.total_recorded() == base + 1
        assert hold == []  # drained: double-record impossible

    def test_error_with_hold_records_immediately(self, db):
        if db.scheduler is None or db.slo is None:
            pytest.skip("scheduler/slo disabled in this config")
        base = db.slo.total_recorded()
        hold = []
        with pytest.raises(Exception):
            db.scheduler.submit("SELECT nope FROM cpu", slo_hold=hold)
        # errored entries never defer (there is no response to time)
        assert db.slo.total_recorded() == base + 1
        db.scheduler.record_held(hold)  # empty: no double count
        assert db.slo.total_recorded() == base + 1

    def test_fast_burn_rejects_background_admission(self, db):
        from greptimedb_tpu.errors import ResourcesExhausted
        from greptimedb_tpu.utils.telemetry import REGISTRY

        if db.scheduler is None or db.slo is None:
            pytest.skip("scheduler/slo disabled in this config")
        db.slo.fast_burn_active = lambda: True
        try:
            with pytest.raises(ResourcesExhausted):
                db.scheduler.submit("SELECT count(v) FROM cpu",
                                    priority="background")
            assert (REGISTRY.value("greptime_scheduler_rejected_total",
                                   ("default", "slo_budget")) or 0) >= 1
        finally:
            del db.slo.fast_burn_active

    def test_slo_status_information_schema(self, db):
        if db.slo is None:
            pytest.skip("slo disabled in this config")
        db.scheduler.submit("SELECT count(v) FROM cpu")
        res = db.sql("SELECT tenant, class, protocol, total "
                     "FROM information_schema.slo_status")
        assert res.rows, "slo_status must render recorded keys"
        cols = dict(zip(res.column_names, zip(*res.rows)))
        assert "default" in cols["tenant"]


class TestOffPin:
    def test_slo_off_means_never_imported(self, tmp_path):
        """GREPTIME_SLO=off: neither slo nor idle module loads, the
        scheduler uses the legacy chained idle dispatcher, and queries
        serve exactly as before."""
        code = """
import os, sys
os.environ["GREPTIME_SLO"] = "off"
os.environ["JAX_PLATFORMS"] = "cpu"
from greptimedb_tpu.standalone import GreptimeDB
d = GreptimeDB()
assert d.slo is None and d.idle_economy is None
d.sql("CREATE TABLE t (h STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, "
      "PRIMARY KEY(h))")
d.sql("INSERT INTO t VALUES ('a', 1000, 1.0)")
if d.scheduler is not None:
    assert d.scheduler.slo is None
    assert d.scheduler.idle_economy is None
    r = d.scheduler.submit("SELECT count(v) FROM t")
    assert r.rows[0][0] == 1
    # the legacy chained dispatcher serves (two hooks mint the chain)
    d.scheduler.add_idle_hook(lambda: False, kick=False)
    d.scheduler.add_idle_hook(lambda: False, kick=False)
    assert getattr(d.scheduler.idle_hook, "_gl_hooks", None) is not None
assert "greptimedb_tpu.serving.slo" not in sys.modules
assert "greptimedb_tpu.serving.idle" not in sys.modules
d.close()
print("OFF-PIN-OK")
"""
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=240)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "OFF-PIN-OK" in out.stdout
