"""Online background integrity scrubber (ISSUE 15 tentpole 1).

The acceptance story: seeded bit rot in a COLD artifact (SST, manifest
file, WAL segment, grid snapshot, S3 cache entry) is found and repaired
by the scrubber BEFORE any query or restart trips over it —
scrub-then-query serves correct bytes, ``greptime_durability_
repaired_total`` increments, restarts that would have quarantined or
silently truncated now open clean.  Pacing pins: the scrubber is
idle-capacity work that yields to interactive queries and resumes
mid-sweep across restarts via its persisted cursor.
"""

import json
import os

import numpy as np
import pytest

from greptimedb_tpu.storage.region import RegionEngine
from greptimedb_tpu.storage.scrubber import Scrubber
from greptimedb_tpu.utils.chaos import CHAOS
from greptimedb_tpu.utils.telemetry import REGISTRY

from tests.test_durability import (  # shared PR-9 fixtures
    cpu_schema, record_offsets, scan_tuples, wal_segment, write_rows,
    _REC_HDR,
)


@pytest.fixture(autouse=True)
def _chaos_clean():
    CHAOS.reset()
    yield
    CHAOS.reset()


def _flip_sst_bytes(store, meta):
    """One flipped byte mid-file: silent rot a read would detect, the
    scrubber must find first."""
    data = bytearray(store.read(meta.path))
    data[len(data) // 2] ^= 0xFF
    # bypass the write discipline on purpose: rot, not a write
    with open(store.local_path(meta.path), "r+b") as f:
        f.write(bytes(data))


class TestSstScrub:
    def test_cold_sst_rot_repaired_before_any_query(self, tmp_data_dir):
        """THE acceptance pin (a): scrub-then-query serves correct
        bytes; the repair counter increments; no query ever saw the
        corruption."""
        engine = RegionEngine(tmp_data_dir)
        region = engine.create_region(1, cpu_schema())
        write_rows(region, n=12)
        region.flush()
        expect = scan_tuples(region)
        meta = region.sst_files[0]
        _flip_sst_bytes(engine.store, meta)
        r0 = REGISTRY.value("greptime_durability_repaired_total",
                            ("sst", "wal")) or 0.0
        scrub = Scrubber(engine, interval_s=0, batch=100)
        out = scrub.run_sweep()
        assert out["corrupt"] == 1
        # repaired from the WAL re-flush (the records are still in the
        # active segment) — BEFORE any query read the region
        assert REGISTRY.value("greptime_durability_repaired_total",
                              ("sst", "wal")) == r0 + 1
        assert scan_tuples(region) == expect
        # the rotted original is preserved, never deleted
        assert any(p.endswith(".quarantine")
                   for p in engine.store.list("region_1/sst"))
        # a second sweep over the repaired region is clean
        assert scrub.run_sweep()["corrupt"] == 0
        engine.close()

    def test_clean_region_sweeps_clean(self, tmp_data_dir):
        engine = RegionEngine(tmp_data_dir)
        region = engine.create_region(1, cpu_schema())
        write_rows(region, n=8)
        region.flush()
        write_rows(region, n=8, t0=100_000)
        scrub = Scrubber(engine, interval_s=0, batch=100)
        out = scrub.run_sweep()
        assert out["corrupt"] == 0 and out["items"] >= 3
        assert scrub.sweeps == 1
        engine.close()


class TestManifestScrub:
    def test_rotted_delta_repaired_before_restart_needs_it(
            self, tmp_data_dir):
        """Without the scrubber this rot is found at the NEXT OPEN —
        possibly quarantining the region.  The scrubber repairs it from
        live state: quarantine + forced verified checkpoint, and the
        restart opens clean."""
        engine = RegionEngine(tmp_data_dir)
        region = engine.create_region(1, cpu_schema())
        write_rows(region, n=6)
        region.flush()
        expect = scan_tuples(region)
        deltas = [p for p in engine.store.list("region_1/manifest")
                  if "/delta-" in p]
        victim = engine.store.local_path(deltas[-1])
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0x10
        open(victim, "wb").write(bytes(data))
        r0 = REGISTRY.value(
            "greptime_durability_repaired_total",
            ("manifest", "scrub_checkpoint")) or 0.0
        scrub = Scrubber(engine, interval_s=0, batch=100)
        assert scrub.run_sweep()["corrupt"] >= 1
        assert REGISTRY.value(
            "greptime_durability_repaired_total",
            ("manifest", "scrub_checkpoint")) == r0 + 1
        # suspect preserved under quarantine/
        assert any("/quarantine/" in p
                   for p in engine.store.list("region_1/manifest"))
        # the quarantined corpse is NOT re-flagged: later sweeps are
        # clean (no perpetual repair/alert loop, bytes stay preserved)
        assert scrub.run_sweep()["corrupt"] == 0
        assert any("/quarantine/" in p
                   for p in engine.store.list("region_1/manifest"))
        engine.close()
        # restart opens CLEAN from the fresh checkpoint — no
        # ManifestCorruption, no region quarantine, bit-exact rows
        engine2 = RegionEngine(tmp_data_dir)
        assert scan_tuples(engine2.open_region(1)) == expect
        engine2.close()


class TestWalScrub:
    def _region_with_wal_tail(self, home, batches=5):
        engine = RegionEngine(home)
        region = engine.create_region(1, cpu_schema())
        for b in range(batches):
            write_rows(region, n=6, t0=b * 100_000, v0=b * 10.0)
        return engine, region

    def _corrupt_seq(self, home, seq):
        seg = wal_segment(os.path.join(home, "region_1", "wal"))
        data = bytearray(open(seg, "rb").read())
        off, _ln = record_offsets(bytes(data))[seq]
        data[off + _REC_HDR + 5] ^= 0x08
        open(seg, "wb").write(bytes(data))
        return seg

    def test_interior_rot_flush_covered_zero_loss(self, tmp_data_dir):
        """No resync source: the scrubber flushes — the memtable still
        holds every acked row, so the damaged log becomes irrelevant.
        Without the scrub, the next crash's replay would raise WalHole
        (uncovered acked loss)."""
        engine, region = self._region_with_wal_tail(tmp_data_dir)
        expect = scan_tuples(region)
        self._corrupt_seq(tmp_data_dir, seq=3)
        out = region.scrub_wal()
        assert out["damage"] == 1 and out["flushed"]
        assert scan_tuples(region) == expect
        engine.close(flush=False)
        # restart replays clean — zero acked loss, no WalHole
        engine2 = RegionEngine(tmp_data_dir)
        assert scan_tuples(engine2.open_region(1)) == expect
        engine2.close()

    def test_interior_rot_resynced_without_flush(self, tmp_data_dir):
        """With a resync source (follower WAL / remote broker) the lost
        range re-logs in place — no forced flush, no structure change."""
        import shutil

        from greptimedb_tpu.storage.durability import resync_from_log_store
        from greptimedb_tpu.storage.wal import FileLogStore

        engine, region = self._region_with_wal_tail(tmp_data_dir)
        expect = scan_tuples(region)
        wal_dir = os.path.join(tmp_data_dir, "region_1", "wal")
        pristine = str(tmp_data_dir) + "_pristine"
        region.wal._fh.flush()
        shutil.copytree(wal_dir, pristine)
        self._corrupt_seq(tmp_data_dir, seq=3)
        follower = FileLogStore(pristine)
        region.wal_resync = resync_from_log_store(follower)
        out = region.scrub_wal()
        assert out == {"damage": 1, "repaired": 1, "flushed": False}
        assert region.sst_files == []  # no forced flush
        follower.close()
        engine.close(flush=False)
        engine2 = RegionEngine(tmp_data_dir)
        assert scan_tuples(engine2.open_region(1)) == expect
        engine2.close()

    def test_tail_rot_resynced_into_fresh_segment(self, tmp_data_dir):
        """Tail rot + a covering resync source: the re-logged records
        must survive the tail truncation (fresh segment), and the
        recovery is durable BEFORE the damage drops — a crash anywhere
        mid-scrub leaves the corruption loud, never silently clean."""
        import shutil

        from greptimedb_tpu.storage.durability import resync_from_log_store
        from greptimedb_tpu.storage.wal import FileLogStore

        engine, region = self._region_with_wal_tail(tmp_data_dir,
                                                    batches=4)
        expect = scan_tuples(region)
        wal_dir = os.path.join(tmp_data_dir, "region_1", "wal")
        pristine = str(tmp_data_dir) + "_pristine"
        region.wal._fh.flush()
        shutil.copytree(wal_dir, pristine)
        self._corrupt_seq(tmp_data_dir, seq=4)  # the newest record
        follower = FileLogStore(pristine)
        region.wal_resync = resync_from_log_store(follower)
        out = region.scrub_wal()
        assert out == {"damage": 1, "repaired": 1, "flushed": False}
        assert region.sst_files == []  # repaired in the log, no flush
        follower.close()
        engine.close(flush=False)
        engine2 = RegionEngine(tmp_data_dir)
        region2 = engine2.open_region(1)
        assert scan_tuples(region2) == expect  # zero acked loss
        assert not region2.wal.last_triage  # and the log is clean
        engine2.close()

    def test_tail_rot_is_acked_loss_not_debris(self, tmp_data_dir):
        """Bit rot in the LAST record: a crash-time replay would
        truncate it as torn-tail debris — silently losing an acked
        batch.  The live scrubber knows everything in the log was
        acked and flush-covers it instead."""
        engine, region = self._region_with_wal_tail(tmp_data_dir,
                                                    batches=4)
        expect = scan_tuples(region)
        self._corrupt_seq(tmp_data_dir, seq=4)  # the newest record
        out = region.scrub_wal()
        assert out["damage"] == 1 and out["flushed"]
        engine.close(flush=False)
        engine2 = RegionEngine(tmp_data_dir)
        assert scan_tuples(engine2.open_region(1)) == expect  # zero loss
        engine2.close()

    def test_scrub_wal_noop_on_clean_log(self, tmp_data_dir):
        engine, region = self._region_with_wal_tail(tmp_data_dir)
        gen = region.generation
        assert region.scrub_wal() == {"damage": 0, "repaired": 0,
                                      "flushed": False}
        assert region.generation == gen  # zero side effects
        engine.close()


class TestSnapshotScrub:
    def test_corrupt_snapshot_quarantined(self, tmp_path, tmp_data_dir):
        from greptimedb_tpu.storage.grid import GridTable, save_grid_snapshot

        engine = RegionEngine(tmp_data_dir)
        region = engine.create_region(1, cpu_schema())
        write_rows(region, n=4)
        table = GridTable(
            values=np.zeros((1, 3, 4), dtype=np.float32),
            valid=np.ones((3, 4), dtype=bool),
            tag_codes={"hostname": np.zeros(3, dtype=np.int32)},
            ts0=0, step=1000, nt=4, num_series=3,
            field_names=("v",), dicts={"hostname": ["h0", "h1", "h2"]},
            no_nan=(True,), dicts_version=1, region_id=1,
        )
        snap = str(tmp_path / "grid_snap")
        save_grid_snapshot(table, region, snap)
        # rot the tensor container (truncated npz = BadZipFile shape)
        with open(os.path.join(snap, "tags.npz"), "r+b") as f:
            f.truncate(10)
        scrub = Scrubber(engine, interval_s=0, batch=100,
                         snapshot_dirs=[snap])
        out = scrub.run_sweep()
        assert out["corrupt"] == 1
        assert os.path.exists(os.path.join(snap, "meta.json.quarantine"))
        # load now refuses instead of crashing → SST-build fallback
        from greptimedb_tpu.storage.grid import load_grid_snapshot

        assert load_grid_snapshot(snap, region) is None
        engine.close()


class TestS3CacheScrub:
    def test_stale_cache_entries_evicted(self, tmp_path):
        from greptimedb_tpu.storage.s3 import MockS3Server, S3ObjectStore

        srv = MockS3Server()
        try:
            writer = S3ObjectStore(srv.endpoint, "bkt", access_key="k",
                                   secret_key="s")
            cache = str(tmp_path / "cache")
            store = S3ObjectStore(srv.endpoint, "bkt", access_key="k",
                                  secret_key="s", cache_dir=cache)
            store.write("region_1/sst/aaa.parquet", b"old-bytes")
            store.write("region_1/sst/bbb.parquet", b"keep-bytes")
            # another node replaces one object and deletes nothing
            writer.write("region_1/sst/aaa.parquet", b"new-bytes!")
            engine = RegionEngine(str(tmp_path / "home"), store=store)
            scrub = Scrubber(engine, interval_s=0, batch=100)
            out = scrub.run_sweep()
            assert out["corrupt"] == 1  # the stale entry, evicted
            assert not os.path.exists(
                store._cache_path("region_1/sst/aaa.parquet"))
            assert os.path.exists(
                store._cache_path("region_1/sst/bbb.parquet"))
            # next read refetches the fresh remote bytes
            assert store.read("region_1/sst/aaa.parquet") == b"new-bytes!"
        finally:
            srv.stop()


class TestPacing:
    def _engine_with_ssts(self, home, n=4):
        from greptimedb_tpu.storage.region import RegionOptions

        engine = RegionEngine(home)
        # compaction off: these tests count exactly n live SST items
        region = engine.create_region(
            1, cpu_schema(), RegionOptions(compaction_trigger_files=999))
        for b in range(n):
            write_rows(region, n=4, t0=b * 100_000)
            region.flush()
        return engine, region

    def test_preemption_pin_zero_items_while_interactive_waits(
            self, tmp_data_dir):
        """Acceptance pin (d): interactive pressure preempts the
        scrubber — a tick under load verifies NOTHING."""
        engine, _region = self._engine_with_ssts(tmp_data_dir)
        waiting = [True]
        scrub = Scrubber(engine, interval_s=0, batch=100,
                         should_yield=lambda: waiting[0])
        y0 = REGISTRY.value("greptime_scrub_yield_total") or 0.0
        assert scrub.tick() is True  # stays hooked
        assert scrub.items == 0 and scrub.sweeps == 0
        assert REGISTRY.value("greptime_scrub_yield_total") == y0 + 1
        # pressure gone: the same tick machinery makes progress
        waiting[0] = False
        while scrub.sweeps == 0:
            scrub.tick()
        assert scrub.items > 0
        engine.close()

    def test_yield_mid_batch(self, tmp_data_dir):
        """Preemption is per-ITEM, not per-tick: pressure arriving mid
        batch stops the batch."""
        engine, _region = self._engine_with_ssts(tmp_data_dir)
        calls = []
        scrub = Scrubber(engine, interval_s=0, batch=100,
                         should_yield=lambda: len(calls) >= 2)
        real = scrub._scrub_item
        scrub._scrub_item = lambda it, force=False: (
            calls.append(it), real(it, force=force))[1]
        scrub.tick()
        assert len(calls) == 2  # batch of 100 stopped after 2 items
        engine.close()

    def test_interval_gates_resweeps(self, tmp_data_dir):
        engine, _region = self._engine_with_ssts(tmp_data_dir, n=1)
        scrub = Scrubber(engine, interval_s=3600, batch=100)
        while scrub.sweeps == 0:
            scrub.tick()
        items = scrub.items
        for _ in range(5):
            scrub.tick()  # within the interval: no new sweep starts
        assert scrub.sweeps == 1 and scrub.items == items
        engine.close()

    def test_cursor_resumes_mid_sweep_across_restart(self, tmp_data_dir):
        engine, _region = self._engine_with_ssts(tmp_data_dir, n=10)
        scrub = Scrubber(engine, interval_s=0, batch=1)
        for _ in range(9):  # 9 of 12 items (manifest + wal + 10 ssts)
            scrub.tick()
        assert scrub.sweeps == 0
        cur = json.loads(engine.store.read(scrub._cursor_path).decode())
        assert cur["index"] == 8  # persisted every 8 items
        # "restart": a fresh scrubber resumes past the persisted cursor
        # (the cursor path is per data home, so nodes sharing a bucket
        # never clobber each other's position)
        scrub2 = Scrubber(engine, interval_s=0, batch=100)
        assert scrub2._cursor_path == scrub._cursor_path
        assert scrub2._resume_skip == 8
        out = scrub2.run_sweep()
        assert out["items"] == 12 - 8  # only the unscrubbed suffix
        assert not engine.store.exists(scrub._cursor_path)  # cleared
        engine.close()

    def _regroup_sst(self, engine, meta, rows_per_group=2):
        """Rewrite one SST's bytes with tiny row groups (same rows, same
        page checksums) so chunked verify has multiple steps."""
        import io

        import pyarrow.parquet as pq

        table = pq.read_table(io.BytesIO(engine.store.read(meta.path)))
        sink = io.BytesIO()
        pq.write_table(table, sink, row_group_size=rows_per_group,
                       write_page_checksum=True)
        with open(engine.store.local_path(meta.path), "wb") as f:
            f.write(sink.getvalue())
        return pq.ParquetFile(
            io.BytesIO(sink.getvalue())).metadata.num_row_groups

    def test_preemption_mid_sst_resumes_between_row_groups(
            self, tmp_data_dir):
        """ISSUE 18 satellite pin: a large SST verifies row group by row
        group; interactive pressure arriving MID-FILE stashes the
        half-drained verify and the next idle tick resumes it — without
        re-reading the bytes or restarting the decode."""
        engine, region = self._engine_with_ssts(tmp_data_dir, n=1)
        meta = region.sst_files[0]
        groups = self._regroup_sst(engine, meta)
        assert groups >= 2
        state = {"armed": False, "calls": 0}

        def should_yield():
            if not state["armed"]:
                return False
            state["calls"] += 1
            # the tick-start and loop-top probes pass; the first BETWEEN-
            # ROW-GROUPS probe inside the sst verify fires the preempt
            return state["calls"] > 2

        scrub = Scrubber(engine, interval_s=0, batch=1,
                         should_yield=should_yield)
        reads = []
        real_read = engine.store.read

        def counting_read(path):
            if path == meta.path:
                reads.append(path)
            return real_read(path)

        engine.store.read = counting_read
        try:
            while scrub.items < 2:  # manifest + wal verified
                scrub.tick()
            state["armed"] = True
            scrub.tick()  # starts the sst, preempts after one row group
            assert scrub._pending_item is not None
            assert scrub._pending_item[0] == "sst"
            assert scrub._sst_gen is not None
            assert scrub.items == 2  # the half-verified sst NOT counted
            assert len(reads) == 1   # bytes read exactly once so far
            # pressure clears: the stashed verify resumes where it left
            # off — no second read of the file, the item completes
            state["armed"] = False
            scrub.tick()
            assert scrub.items == 3
            assert scrub._pending_item is None and scrub._sst_gen is None
            assert len(reads) == 1
        finally:
            engine.store.read = real_read
        engine.close()

    def test_force_sweep_never_yields_mid_item(self, tmp_data_dir):
        """run_sweep (admin/tests) drains whole items even under
        pressure: the force path skips the between-groups preempt."""
        engine, region = self._engine_with_ssts(tmp_data_dir, n=1)
        meta = region.sst_files[0]
        self._regroup_sst(engine, meta)
        scrub = Scrubber(engine, interval_s=0, batch=100,
                         should_yield=lambda: True)  # max pressure
        out = scrub.run_sweep()
        assert scrub.sweeps == 1 and out["corrupt"] == 0
        assert scrub._pending_item is None and scrub._sst_gen is None
        engine.close()

    def test_chaos_scrub_read_error_does_not_kill_sweep(
            self, tmp_data_dir):
        engine, _region = self._engine_with_ssts(tmp_data_dir)
        CHAOS.rule("scrub.read", 1.0, "error", limit=2)
        scrub = Scrubber(engine, interval_s=0, batch=100)
        out = scrub.run_sweep()
        # two items errored (counted), the rest verified, sweep finished
        assert scrub.sweeps == 1
        assert out["items"] >= 4
        engine.close()


class TestStandaloneWiring:
    def test_auto_arms_for_persistent_homes(self, tmp_path, monkeypatch):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from greptimedb_tpu.standalone import GreptimeDB

        monkeypatch.delenv("GREPTIME_SCRUB", raising=False)
        db = GreptimeDB(str(tmp_path / "home"))
        try:
            assert db.scrubber is not None
            assert db.scheduler.idle_hook is not None
            # auto mode must NOT spin the worker pool for embedders
            assert not db.scheduler._started
        finally:
            db.close()

    def test_off_and_memory_mode_disable(self, tmp_path, monkeypatch):
        from greptimedb_tpu.standalone import GreptimeDB

        monkeypatch.setenv("GREPTIME_SCRUB", "off")
        db = GreptimeDB(str(tmp_path / "home"))
        try:
            assert db.scrubber is None
        finally:
            db.close()
        monkeypatch.delenv("GREPTIME_SCRUB", raising=False)
        db = GreptimeDB()  # memory mode
        try:
            assert db.scrubber is None
        finally:
            db.close()

    def test_scrub_on_serving_instance_end_to_end(self, tmp_path,
                                                  monkeypatch):
        """GREPTIME_SCRUB=on + seeded SST rot: the serving instance's
        own idle loop finds and repairs it, and SQL over the repaired
        region is correct (scrub-then-query)."""
        import time as _time

        from greptimedb_tpu.standalone import GreptimeDB

        monkeypatch.setenv("GREPTIME_SCRUB", "on")
        monkeypatch.setenv("GREPTIME_SCRUB_INTERVAL_S", "0")
        home = str(tmp_path / "home")
        db = GreptimeDB(home)
        try:
            db.sql("CREATE TABLE m (h STRING, ts TIMESTAMP(3) TIME "
                   "INDEX, v DOUBLE, PRIMARY KEY (h))")
            db.sql("INSERT INTO m VALUES " + ",".join(
                f"('h{i%3}',{1000 + i},{float(i)})" for i in range(12)))
            region = db._region_of("m")
            region.flush()
            want = db.sql("SELECT h, ts, v FROM m ORDER BY ts, h").rows
            _flip_sst_bytes(db.regions.store, region.sst_files[0])
            deadline = _time.time() + 30
            while db.scrubber.corrupt == 0 and _time.time() < deadline:
                _time.sleep(0.05)
            assert db.scrubber.corrupt >= 1, "idle loop never found rot"
            assert db.sql("SELECT h, ts, v FROM m ORDER BY ts, h"
                          ).rows == want
        finally:
            db.close()


class TestChunkedVerify:
    """iter_verify_sst_bytes (ISSUE 18 satellite): row-group-granular
    checksummed verify — the unit the scrubber preempts between."""

    def _bytes(self, n_rows=8, rows_per_group=2):
        import io

        import pyarrow as pa
        import pyarrow.parquet as pq

        table = pa.table({"ts": list(range(n_rows)),
                          "v": [float(i) for i in range(n_rows)]})
        sink = io.BytesIO()
        pq.write_table(table, sink, row_group_size=rows_per_group,
                       write_page_checksum=True)
        return sink.getvalue()

    def test_clean_file_yields_one_true_per_row_group(self):
        from greptimedb_tpu.storage.sst import (
            iter_verify_sst_bytes, verify_sst_bytes,
        )

        data = self._bytes(n_rows=8, rows_per_group=2)
        assert list(iter_verify_sst_bytes(data)) == [True] * 4
        assert verify_sst_bytes(data)

    def test_corrupt_group_stops_iteration_with_false(self):
        from greptimedb_tpu.storage.sst import (
            iter_verify_sst_bytes, verify_sst_bytes,
        )

        data = bytearray(self._bytes(n_rows=64, rows_per_group=8))
        # flip a byte in the data region (past the magic, before the
        # footer): some group fails its page checksum
        data[len(data) // 3] ^= 0xFF
        out = list(iter_verify_sst_bytes(bytes(data)))
        assert out[-1] is False
        assert all(out[:-1])
        assert not verify_sst_bytes(bytes(data))

    def test_garbage_bytes_yield_single_false(self):
        from greptimedb_tpu.storage.sst import (
            iter_verify_sst_bytes, verify_sst_bytes,
        )

        assert list(iter_verify_sst_bytes(b"not a parquet file")) == [False]
        assert not verify_sst_bytes(b"not a parquet file")
