"""Golden-file SQL/TQL corpus (the sqlness tier).

Mirrors the reference's sqlness golden tests (tests/cases/standalone —
454 .sql files with .result goldens, runner in tests/runner/): each
``tests/golden/*.sql`` file holds ;-separated statements executed against
a fresh standalone instance; expected output lives in the matching
``.result`` file.  Numeric cells compare with float tolerance (TPU f32
vs reference f64 — SURVEY §4 'numeric goldens must tolerate TPU float
differences').

Regenerate after INTENDED behavior changes with:
    GREPTIME_GOLDEN_UPDATE=1 python -m pytest tests/test_golden.py -q
then review the .result diff like any code change.
"""

import math
import os
import re

import pytest

from greptimedb_tpu.standalone import GreptimeDB

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
UPDATE = bool(os.environ.get("GREPTIME_GOLDEN_UPDATE"))

pytestmark = pytest.mark.golden


def _cases():
    return sorted(
        f[:-4] for f in os.listdir(GOLDEN_DIR) if f.endswith(".sql")
    )


def _strip_comments(text: str) -> str:
    """Remove -- comments (outside string literals), line by line."""
    out_lines = []
    for line in text.splitlines():
        in_str = False
        cut = len(line)
        for i, ch in enumerate(line):
            if ch == "'":
                in_str = not in_str
            elif not in_str and line.startswith("--", i):
                cut = i
                break
        out_lines.append(line[:cut])
    return "\n".join(out_lines)


def _split_statements(text: str) -> list[str]:
    text = _strip_comments(text)
    out, buf, in_str = [], [], False
    for ch in text:
        if ch == "'":
            in_str = not in_str
        if ch == ";" and not in_str:
            stmt = "".join(buf).strip()
            if stmt:
                out.append(stmt)
            buf = []
        else:
            buf.append(ch)
    tail = "".join(buf).strip()
    if tail:
        out.append(tail)
    return out


def _fmt_cell(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        return f"{v:.6g}"
    return str(v)


def _run_case(name: str) -> str:
    db = GreptimeDB()
    lines = []
    try:
        path = os.path.join(GOLDEN_DIR, name + ".sql")
        with open(path) as f:
            text = f.read()
        for stmt in _split_statements(text):
            lines.append(f">> {stmt}")
            try:
                res = db.sql(stmt)
                if res.column_names:
                    lines.append("| " + " | ".join(res.column_names) + " |")
                    for row in res.rows:
                        lines.append(
                            "| " + " | ".join(_fmt_cell(v) for v in row)
                            + " |"
                        )
                else:
                    lines.append(f"OK affected={res.affected_rows}")
            except Exception as e:  # noqa: BLE001 — errors ARE the golden
                lines.append(f"ERROR[{type(e).__name__}]")
            lines.append("")
    finally:
        db.close()
    return "\n".join(lines).rstrip() + "\n"


_NUM = re.compile(r"^-?\d+(\.\d+)?([eE][+-]?\d+)?$")


def _rows_match(got: str, want: str) -> bool:
    """Line-by-line compare; numeric cells at 1e-5 relative tolerance."""
    glines = got.splitlines()
    wlines = want.splitlines()
    if len(glines) != len(wlines):
        return False
    for g, w in zip(glines, wlines):
        if g == w:
            continue
        gc = [c.strip() for c in g.strip("|").split("|")]
        wc = [c.strip() for c in w.strip("|").split("|")]
        if len(gc) != len(wc):
            return False
        for a, b in zip(gc, wc):
            if a == b:
                continue
            if _NUM.match(a) and _NUM.match(b):
                fa, fb = float(a), float(b)
                if abs(fa - fb) <= 1e-5 * max(1.0, abs(fb)):
                    continue
            return False
    return True


@pytest.mark.parametrize("name", _cases())
def test_golden(name):
    got = _run_case(name)
    rpath = os.path.join(GOLDEN_DIR, name + ".result")
    if UPDATE or not os.path.exists(rpath):
        with open(rpath, "w") as f:
            f.write(got)
        if UPDATE:
            pytest.skip("golden updated")
        pytest.fail(f"golden {name}.result was missing; generated — review it")
    with open(rpath) as f:
        want = f.read()
    assert _rows_match(got, want), (
        f"golden mismatch for {name}\n--- got ---\n{got}\n--- want ---\n{want}"
    )
