"""Remote (shared) WAL pruning + multiplexing edge cases (ISSUE 6
satellite; reference src/meta-srv/src/procedure/wal_prune/ + the
WalEntryDistributor demux in src/mito2/src/wal/).

Segment rolling is forced small via the wal module's target constant so
whole-segment pruning is observable with a handful of entries.
"""

import os

import pytest

from greptimedb_tpu.storage import wal as wal_mod
from greptimedb_tpu.storage.remote_wal import RemoteLogStore, SharedLogBroker


@pytest.fixture
def small_segments(monkeypatch):
    # every append rolls quickly: ~1 record per segment at this size
    monkeypatch.setattr(wal_mod, "_SEGMENT_TARGET", 64)


def _topic_segments(root: str, topic: str) -> list[str]:
    d = os.path.join(root, topic)
    return sorted(f for f in os.listdir(d) if f.endswith(".wal"))


class TestPruning:
    def test_low_watermark_drops_whole_segments(self, tmp_path,
                                                small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=7)
        for seq in range(1, 11):
            store.append(seq, b"payload-%d" % seq)
        before = _topic_segments(root, store.topic)
        assert len(before) >= 5  # rolling actually happened
        # region flushed everything below 8: segments whose every entry
        # is below the watermark disappear from disk
        store.truncate(8)
        after = _topic_segments(root, store.topic)
        assert len(after) < len(before)
        # replay starts at the stored floor and yields exactly the
        # unpruned suffix
        assert [seq for seq, _p in store.replay(0)] == [8, 9, 10]
        # appends continue cleanly past a prune
        store.append(11, b"payload-11")
        assert [seq for seq, _p in store.replay(8)] == [8, 9, 10, 11]

    def test_floor_persisted_across_broker_restart(self, tmp_path,
                                                   small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=3)
        for seq in range(1, 8):
            store.append(seq, b"x%d" % seq)
        store.truncate(6)
        broker.close()
        # a fresh broker instance (failover / restart) sees the floor and
        # the surviving tail, and appends at non-colliding offsets
        broker2 = SharedLogBroker(root)
        store2 = RemoteLogStore(broker2, region_id=3)
        assert [seq for seq, _p in store2.replay(0)] == [6, 7]
        store2.append(8, b"x8")
        assert [seq for seq, _p in store2.replay(6)] == [6, 7, 8]

    def test_corrupt_watermark_marker_prunes_nothing(self, tmp_path,
                                                     small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=1)
        for seq in range(1, 6):
            store.append(seq, b"p%d" % seq)
        # corrupt the marker: pruning must degrade to keep-everything
        with open(os.path.join(root, f"{store.topic}.watermarks.json"),
                  "w") as f:
            f.write("{not json")
        store.truncate(4)  # rewrites the marker from scratch
        assert [seq for seq, _p in store.replay(0)][-1] == 5


class TestMultiplexedTopics:
    def test_regions_replay_independently_after_pruning(self, tmp_path,
                                                        small_segments):
        """Two regions multiplex one topic; one region's flush/prune must
        not lose the other's unflushed entries."""
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, topics_per_node=1)
        r1 = RemoteLogStore(broker, region_id=1)
        r2 = RemoteLogStore(broker, region_id=2)
        assert r1.topic == r2.topic  # actually multiplexed
        for seq in range(1, 6):
            r1.append(seq, b"r1-%d" % seq)
            r2.append(seq, b"r2-%d" % seq)
        # region 1 flushed everything; region 2 flushed nothing
        r1.truncate(6)
        # region 2 still replays its full history (its watermark pins
        # every shared segment)
        assert [seq for seq, _p in r2.replay(0)] == [1, 2, 3, 4, 5]
        assert [p for _s, p in r2.replay(0)][0] == b"r2-1"
        # nothing of region 1 leaks into region 2's stream
        assert all(p.startswith(b"r2-") for _s, p in r2.replay(0))
        # now region 2 flushes too: shared segments become prunable
        before = _topic_segments(root, r2.topic)
        r2.truncate(4)
        after = _topic_segments(root, r2.topic)
        assert len(after) < len(before)
        # both regions replay exactly their unflushed suffixes from their
        # own flush baselines (r1 entries pinned in shared segments by
        # r2's watermark are skipped by replay-from-flushed-seq, which is
        # how a real region opens: replay(flushed_seq + 1))
        assert [seq for seq, _p in r1.replay(6)] == []
        assert [seq for seq, _p in r2.replay(4)] == [4, 5]

    def test_replication_multiplexed_prune_intact(self, tmp_path,
                                                  small_segments):
        """Replication composes with topic multiplexing: quorum appends,
        per-region watermark pruning, per-region replay."""
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, topics_per_node=1, replicas=3)
        r1 = RemoteLogStore(broker, region_id=1)
        r2 = RemoteLogStore(broker, region_id=2)
        for seq in range(1, 6):
            r1.append(seq, b"r1-%d" % seq)
            r2.append(seq, b"r2-%d" % seq)
        r1.truncate(6)
        assert [seq for seq, _p in r2.replay(0)] == [1, 2, 3, 4, 5]
        r2.truncate(4)
        assert [seq for seq, _p in r2.replay(4)] == [4, 5]

    def test_promotion_reacquires_topic_end(self, tmp_path,
                                            small_segments):
        """A second broker instance (the follower's) caches the topic end
        at open; after the leader appends more, promotion must re-read
        the tail before appending (acquire_ownership) or offsets would
        collide and the pruning floor would corrupt."""
        root = str(tmp_path / "broker")
        leader_broker = SharedLogBroker(root)
        leader = RemoteLogStore(leader_broker, region_id=5)
        leader.append(1, b"a")
        follower_broker = SharedLogBroker(root)
        follower = RemoteLogStore(follower_broker, region_id=5)
        list(follower.replay(0))  # follower primes its broker's offsets
        leader.append(2, b"b")  # leader keeps writing after the open
        # promotion: re-acquire, then append
        follower.acquire_ownership()
        follower.append(3, b"c")
        assert [seq for seq, _p in follower.replay(0)] == [1, 2, 3]
        # offsets stayed monotone: pruning by watermark keeps exactness
        follower.truncate(3)
        assert [seq for seq, _p in follower.replay(0)] == [3]


# ---------------------------------------------------------------------------
# Broker-side replication (ISSUE 15 tentpole 3): quorum appends,
# survive-any-single-copy replay, read-repair, chaos coverage.
# ---------------------------------------------------------------------------


def _replica_topic_dir(root, topic, i):
    return (os.path.join(root, topic) if i == 0
            else os.path.join(root, f".replica{i}", topic))


def _corrupt_middle(path):
    """Flip bytes in the middle of a segment (interior corruption)."""
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        mid = len(data) // 2
        for i in range(mid, min(mid + 8, len(data))):
            data[i] ^= 0xFF
        f.seek(0)
        f.write(bytes(data))


class TestBrokerReplication:
    def _seed(self, root, n=8, replicas=3):
        broker = SharedLogBroker(root, replicas=replicas)
        store = RemoteLogStore(broker, region_id=9)
        for seq in range(1, n + 1):
            store.append(seq, b"payload-%d" % seq)
        return broker, store

    def test_replicas_hold_identical_records(self, tmp_path):
        root = str(tmp_path / "broker")
        broker, store = self._seed(root)
        for i in range(3):
            d = _replica_topic_dir(root, store.topic, i)
            assert os.path.isdir(d) and any(
                f.endswith(".wal") for f in os.listdir(d)), i
        from greptimedb_tpu.storage.wal import FileLogStore

        views = []
        for i in range(3):
            log = FileLogStore(_replica_topic_dir(root, store.topic, i))
            views.append(list(log.replay(0, repair=False)))
            log.close()
        assert views[0] == views[1] == views[2]
        assert len(views[0]) == 8

    def test_replay_survives_losing_any_single_replica(self, tmp_path):
        import shutil

        for lost in range(3):
            root = str(tmp_path / f"broker{lost}")
            broker, store = self._seed(root)
            broker.close()
            shutil.rmtree(_replica_topic_dir(root, store.topic, lost))
            broker2 = SharedLogBroker(root, replicas=3)
            store2 = RemoteLogStore(broker2, region_id=9)
            assert [s for s, _p in store2.replay(0, repair=False)] == list(
                range(1, 9)), f"lost replica {lost}"
            broker2.close()

    def test_replay_survives_corrupting_any_single_replica(self, tmp_path):
        for victim in range(3):
            root = str(tmp_path / f"broker{victim}")
            broker, store = self._seed(root)
            broker.close()
            d = _replica_topic_dir(root, store.topic, victim)
            for fn in os.listdir(d):
                if fn.endswith(".wal"):
                    _corrupt_middle(os.path.join(d, fn))
            broker2 = SharedLogBroker(root, replicas=3)
            store2 = RemoteLogStore(broker2, region_id=9)
            got = [(s, p) for s, p in store2.replay(0, repair=True)]
            assert [s for s, _ in got] == list(range(1, 9)), (
                f"corrupt replica {victim}")
            assert got[0][1] == b"payload-1"
            broker2.close()

    def test_read_repair_backfills_lagging_replica(self, tmp_path):
        import shutil

        root = str(tmp_path / "broker")
        broker, store = self._seed(root)
        broker.close()
        victim_dir = _replica_topic_dir(root, store.topic, 2)
        shutil.rmtree(victim_dir)
        from greptimedb_tpu.utils.telemetry import REGISTRY

        before = REGISTRY.value("greptime_broker_read_repair_total") or 0.0
        broker2 = SharedLogBroker(root, replicas=3)
        store2 = RemoteLogStore(broker2, region_id=9)
        assert len(list(store2.replay(0, repair=True))) == 8
        assert REGISTRY.value("greptime_broker_read_repair_total") >= (
            before + 8)
        # the repaired replica now holds the full history on its own
        from greptimedb_tpu.storage.wal import FileLogStore

        log = FileLogStore(victim_dir)
        assert len(list(log.replay(0, repair=False))) == 8
        log.close()
        broker2.close()

    def test_follower_read_never_repairs(self, tmp_path):
        import shutil

        root = str(tmp_path / "broker")
        broker, store = self._seed(root)
        broker.close()
        victim_dir = _replica_topic_dir(root, store.topic, 1)
        shutil.rmtree(victim_dir)
        broker2 = SharedLogBroker(root, replicas=3)
        follower = RemoteLogStore(broker2, region_id=9)
        assert len(list(follower.replay(0, repair=False))) == 8
        # read-only replay backfilled NOTHING (well, _logs_for recreated
        # the empty dir — but no records were written into it)
        from greptimedb_tpu.storage.wal import FileLogStore

        log = FileLogStore(victim_dir)
        assert list(log.replay(0, repair=False)) == []
        log.close()
        broker2.close()

    def test_quorum_append_tolerates_one_failing_replica(self, tmp_path):
        from greptimedb_tpu.utils.chaos import CHAOS
        from greptimedb_tpu.utils.telemetry import REGISTRY

        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, replicas=3)
        store = RemoteLogStore(broker, region_id=9)
        store.append(1, b"ok")
        try:
            # the append's SECOND replica call errors: exactly one
            # replica misses the record, the 2/3 quorum still acks
            CHAOS.rule("broker.replica", 1.0, "error", at=2)
            store.append(2, b"with-one-down")
        finally:
            CHAOS.reset()
        store.append(3, b"healed-next")
        assert [s for s, _p in store.replay(0, repair=True)] == [1, 2, 3]
        assert REGISTRY.value("greptime_broker_replica_append_total",
                              ("failed",)) >= 1.0

    def test_append_fails_loudly_below_quorum(self, tmp_path):
        from greptimedb_tpu.errors import StorageError
        from greptimedb_tpu.utils.chaos import CHAOS

        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, replicas=3)
        store = RemoteLogStore(broker, region_id=9)
        store.append(1, b"ok")
        try:
            CHAOS.rule("broker.replica", 1.0, "error")  # ALL replicas
            with pytest.raises(StorageError):
                store.append(2, b"nobody-heard-this")
        finally:
            CHAOS.reset()
        # nothing acked, nothing half-visible after the quorum failure
        store.append(2, b"retried")
        assert [p for _s, p in store.replay(0, repair=True)] == [
            b"ok", b"retried"]

    def test_single_replica_keeps_legacy_layout(self, tmp_path):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, replicas=1)
        store = RemoteLogStore(broker, region_id=9)
        store.append(1, b"x")
        assert os.path.isdir(os.path.join(root, store.topic))
        assert not os.path.isdir(os.path.join(root, ".replica1"))
        broker.close()

    def test_raising_replication_factor_adopts_legacy_data(self, tmp_path):
        """replicas=1 history becomes replica 0; read-repair backfills
        the new copies on the first owner replay."""
        root = str(tmp_path / "broker")
        b1 = SharedLogBroker(root, replicas=1)
        s1 = RemoteLogStore(b1, region_id=9)
        for seq in (1, 2, 3):
            s1.append(seq, b"old-%d" % seq)
        b1.close()
        b3 = SharedLogBroker(root, replicas=3)
        s3 = RemoteLogStore(b3, region_id=9)
        assert [s for s, _p in s3.replay(0, repair=True)] == [1, 2, 3]
        s3.append(4, b"new-4")
        from greptimedb_tpu.storage.wal import FileLogStore

        log = FileLogStore(_replica_topic_dir(root, s3.topic, 2))
        assert len(list(log.replay(0, repair=False))) == 4
        log.close()
        b3.close()
