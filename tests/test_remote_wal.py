"""Remote (shared) WAL pruning + multiplexing edge cases (ISSUE 6
satellite; reference src/meta-srv/src/procedure/wal_prune/ + the
WalEntryDistributor demux in src/mito2/src/wal/).

Segment rolling is forced small via the wal module's target constant so
whole-segment pruning is observable with a handful of entries.
"""

import os

import pytest

from greptimedb_tpu.storage import wal as wal_mod
from greptimedb_tpu.storage.remote_wal import RemoteLogStore, SharedLogBroker


@pytest.fixture
def small_segments(monkeypatch):
    # every append rolls quickly: ~1 record per segment at this size
    monkeypatch.setattr(wal_mod, "_SEGMENT_TARGET", 64)


def _topic_segments(root: str, topic: str) -> list[str]:
    d = os.path.join(root, topic)
    return sorted(f for f in os.listdir(d) if f.endswith(".wal"))


class TestPruning:
    def test_low_watermark_drops_whole_segments(self, tmp_path,
                                                small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=7)
        for seq in range(1, 11):
            store.append(seq, b"payload-%d" % seq)
        before = _topic_segments(root, store.topic)
        assert len(before) >= 5  # rolling actually happened
        # region flushed everything below 8: segments whose every entry
        # is below the watermark disappear from disk
        store.truncate(8)
        after = _topic_segments(root, store.topic)
        assert len(after) < len(before)
        # replay starts at the stored floor and yields exactly the
        # unpruned suffix
        assert [seq for seq, _p in store.replay(0)] == [8, 9, 10]
        # appends continue cleanly past a prune
        store.append(11, b"payload-11")
        assert [seq for seq, _p in store.replay(8)] == [8, 9, 10, 11]

    def test_floor_persisted_across_broker_restart(self, tmp_path,
                                                   small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=3)
        for seq in range(1, 8):
            store.append(seq, b"x%d" % seq)
        store.truncate(6)
        broker.close()
        # a fresh broker instance (failover / restart) sees the floor and
        # the surviving tail, and appends at non-colliding offsets
        broker2 = SharedLogBroker(root)
        store2 = RemoteLogStore(broker2, region_id=3)
        assert [seq for seq, _p in store2.replay(0)] == [6, 7]
        store2.append(8, b"x8")
        assert [seq for seq, _p in store2.replay(6)] == [6, 7, 8]

    def test_corrupt_watermark_marker_prunes_nothing(self, tmp_path,
                                                     small_segments):
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root)
        store = RemoteLogStore(broker, region_id=1)
        for seq in range(1, 6):
            store.append(seq, b"p%d" % seq)
        # corrupt the marker: pruning must degrade to keep-everything
        with open(os.path.join(root, f"{store.topic}.watermarks.json"),
                  "w") as f:
            f.write("{not json")
        store.truncate(4)  # rewrites the marker from scratch
        assert [seq for seq, _p in store.replay(0)][-1] == 5


class TestMultiplexedTopics:
    def test_regions_replay_independently_after_pruning(self, tmp_path,
                                                        small_segments):
        """Two regions multiplex one topic; one region's flush/prune must
        not lose the other's unflushed entries."""
        root = str(tmp_path / "broker")
        broker = SharedLogBroker(root, topics_per_node=1)
        r1 = RemoteLogStore(broker, region_id=1)
        r2 = RemoteLogStore(broker, region_id=2)
        assert r1.topic == r2.topic  # actually multiplexed
        for seq in range(1, 6):
            r1.append(seq, b"r1-%d" % seq)
            r2.append(seq, b"r2-%d" % seq)
        # region 1 flushed everything; region 2 flushed nothing
        r1.truncate(6)
        # region 2 still replays its full history (its watermark pins
        # every shared segment)
        assert [seq for seq, _p in r2.replay(0)] == [1, 2, 3, 4, 5]
        assert [p for _s, p in r2.replay(0)][0] == b"r2-1"
        # nothing of region 1 leaks into region 2's stream
        assert all(p.startswith(b"r2-") for _s, p in r2.replay(0))
        # now region 2 flushes too: shared segments become prunable
        before = _topic_segments(root, r2.topic)
        r2.truncate(4)
        after = _topic_segments(root, r2.topic)
        assert len(after) < len(before)
        # both regions replay exactly their unflushed suffixes from their
        # own flush baselines (r1 entries pinned in shared segments by
        # r2's watermark are skipped by replay-from-flushed-seq, which is
        # how a real region opens: replay(flushed_seq + 1))
        assert [seq for seq, _p in r1.replay(6)] == []
        assert [seq for seq, _p in r2.replay(4)] == [4, 5]

    def test_promotion_reacquires_topic_end(self, tmp_path,
                                            small_segments):
        """A second broker instance (the follower's) caches the topic end
        at open; after the leader appends more, promotion must re-read
        the tail before appending (acquire_ownership) or offsets would
        collide and the pruning floor would corrupt."""
        root = str(tmp_path / "broker")
        leader_broker = SharedLogBroker(root)
        leader = RemoteLogStore(leader_broker, region_id=5)
        leader.append(1, b"a")
        follower_broker = SharedLogBroker(root)
        follower = RemoteLogStore(follower_broker, region_id=5)
        list(follower.replay(0))  # follower primes its broker's offsets
        leader.append(2, b"b")  # leader keeps writing after the open
        # promotion: re-acquire, then append
        follower.acquire_ownership()
        follower.append(3, b"c")
        assert [seq for seq, _p in follower.replay(0)] == [1, 2, 3]
        # offsets stayed monotone: pruning by watermark keeps exactness
        follower.truncate(3)
        assert [seq for seq, _p in follower.replay(0)] == [3]
