"""End-to-end SQL tests against the standalone database.

Modeled on the reference's sqlness golden cases (tests/cases/standalone):
DDL, DML, aggregates, time bucketing, range select, introspection.
"""

import numpy as np
import pytest

from greptimedb_tpu.errors import (
    GreptimeError, InvalidArguments, PlanError, SyntaxError_, TableNotFound,
    Unsupported,
)
from greptimedb_tpu.standalone import GreptimeDB


@pytest.fixture
def db():
    d = GreptimeDB()
    yield d
    d.close()


@pytest.fixture
def cpu(db):
    db.sql(
        """CREATE TABLE cpu (
             hostname STRING,
             region STRING,
             ts TIMESTAMP(3) TIME INDEX,
             usage_user DOUBLE,
             usage_system DOUBLE,
             PRIMARY KEY (hostname, region))"""
    )
    db.sql(
        "INSERT INTO cpu (hostname, region, ts, usage_user, usage_system) VALUES "
        "('h1','us-east',0,10.0,1.0),"
        "('h2','us-east',0,20.0,2.0),"
        "('h3','eu-west',0,30.0,3.0),"
        "('h1','us-east',60000,40.0,4.0),"
        "('h2','us-east',60000,50.0,5.0),"
        "('h3','eu-west',60000,60.0,6.0),"
        "('h1','us-east',120000,70.0,7.0)"
    )
    return db


class TestDDL:
    def test_create_show_describe(self, db):
        db.sql("CREATE TABLE t1 (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(a))")
        assert db.sql("SHOW TABLES").rows == [["t1"]]
        desc = db.sql("DESC TABLE t1")
        assert [r[0] for r in desc.rows] == ["a", "ts", "v"]
        assert desc.rows[0][5] == "TAG"
        assert desc.rows[1][5] == "TIMESTAMP"
        assert desc.rows[2][5] == "FIELD"
        sc = db.sql("SHOW CREATE TABLE t1")
        assert "TIME INDEX" in sc.rows[0][1]

    def test_create_if_not_exists(self, db):
        db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        db.sql("CREATE TABLE IF NOT EXISTS t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        with pytest.raises(GreptimeError):
            db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")

    def test_time_index_required_and_typed(self, db):
        with pytest.raises(InvalidArguments):
            db.sql("CREATE TABLE bad (a STRING, v DOUBLE)")
        with pytest.raises(InvalidArguments):
            db.sql("CREATE TABLE bad2 (a STRING, ts DOUBLE TIME INDEX)")

    def test_drop(self, db):
        db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        db.sql("DROP TABLE t")
        assert db.sql("SHOW TABLES").rows == []
        with pytest.raises(TableNotFound):
            db.sql("SELECT * FROM t")
        db.sql("DROP TABLE IF EXISTS t")

    def test_databases(self, db):
        db.sql("CREATE DATABASE mydb")
        assert ["mydb"] in db.sql("SHOW DATABASES").rows
        db.sql("USE mydb")
        db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        assert db.sql("SHOW TABLES").rows == [["t"]]
        db.sql("USE public")
        assert db.sql("SHOW TABLES").rows == []
        # qualified name across db
        db.sql("INSERT INTO mydb.t VALUES (1000, 5.0)")
        assert db.sql("SELECT count(*) FROM mydb.t").rows == [[1]]

    def test_alter_add_column(self, cpu):
        cpu.sql("ALTER TABLE cpu ADD COLUMN mem DOUBLE")
        desc = cpu.sql("DESC TABLE cpu")
        assert "mem" in [r[0] for r in desc.rows]
        cpu.sql(
            "INSERT INTO cpu (hostname, region, ts, usage_user, usage_system, mem)"
            " VALUES ('h9','x',999000,1.0,1.0,77.0)"
        )
        r = cpu.sql("SELECT mem FROM cpu WHERE hostname = 'h9'")
        assert r.rows == [[77.0]]
        # old rows read NULL for the new column
        r = cpu.sql("SELECT mem FROM cpu WHERE hostname = 'h1' AND ts = 0")
        assert r.rows == [[None]]


class TestQueries:
    def test_select_star_where(self, cpu):
        r = cpu.sql("SELECT * FROM cpu WHERE hostname = 'h1' ORDER BY ts")
        assert len(r.rows) == 3
        assert r.column_names == ["hostname", "region", "ts", "usage_user", "usage_system"]
        assert r.rows[0] == ["h1", "us-east", 0, 10.0, 1.0]

    def test_group_by_tag(self, cpu):
        r = cpu.sql(
            "SELECT region, avg(usage_user) FROM cpu GROUP BY region ORDER BY region"
        )
        assert r.rows == [["eu-west", 45.0], ["us-east", 38.0]]

    def test_group_by_two_tags(self, cpu):
        r = cpu.sql(
            "SELECT hostname, region, count(*) FROM cpu GROUP BY hostname, region"
            " ORDER BY hostname"
        )
        assert r.rows == [["h1", "us-east", 3], ["h2", "us-east", 2], ["h3", "eu-west", 2]]

    def test_time_bucket_group(self, cpu):
        r = cpu.sql(
            "SELECT date_bin(INTERVAL '1 minute', ts) m, max(usage_user)"
            " FROM cpu GROUP BY m ORDER BY m"
        )
        assert r.rows == [[0, 30.0], [60000, 60.0], [120000, 70.0]]

    def test_double_groupby(self, cpu):
        r = cpu.sql(
            "SELECT hostname, date_bin(INTERVAL '1 minute', ts) m, avg(usage_user)"
            " FROM cpu GROUP BY hostname, m ORDER BY hostname, m"
        )
        assert r.rows[0] == ["h1", 0, 10.0]
        assert len(r.rows) == 7

    def test_where_time_range(self, cpu):
        r = cpu.sql("SELECT count(*) FROM cpu WHERE ts >= 60000 AND ts < 120000")
        assert r.rows == [[3]]
        r = cpu.sql("SELECT count(*) FROM cpu WHERE ts BETWEEN 0 AND 60000")
        assert r.rows == [[6]]

    def test_where_tag_predicates(self, cpu):
        assert cpu.sql("SELECT count(*) FROM cpu WHERE region != 'us-east'").rows == [[2]]
        assert cpu.sql(
            "SELECT count(*) FROM cpu WHERE hostname IN ('h1','h3')"
        ).rows == [[5]]
        assert cpu.sql(
            "SELECT count(*) FROM cpu WHERE hostname NOT IN ('h1')"
        ).rows == [[4]]
        assert cpu.sql("SELECT count(*) FROM cpu WHERE region LIKE 'us%'").rows == [[5]]
        assert cpu.sql("SELECT count(*) FROM cpu WHERE hostname = 'nope'").rows == [[0]]

    def test_field_predicates(self, cpu):
        assert cpu.sql(
            "SELECT count(*) FROM cpu WHERE usage_user > 25 AND usage_system < 6"
        ).rows == [[3]]
        assert cpu.sql(
            "SELECT count(*) FROM cpu WHERE usage_user BETWEEN 20 AND 50"
        ).rows == [[4]]

    def test_aggregates(self, cpu):
        r = cpu.sql(
            "SELECT count(*), sum(usage_user), min(usage_user), max(usage_user),"
            " avg(usage_user) FROM cpu"
        )
        assert r.rows == [[7, 280.0, 10.0, 70.0, 40.0]]

    def test_first_last_value(self, cpu):
        r = cpu.sql(
            "SELECT hostname, last_value(usage_user), first_value(usage_user)"
            " FROM cpu GROUP BY hostname ORDER BY hostname"
        )
        assert r.rows == [["h1", 70.0, 10.0], ["h2", 50.0, 20.0], ["h3", 60.0, 30.0]]

    def test_stddev(self, cpu):
        r = cpu.sql("SELECT stddev(usage_user) FROM cpu WHERE hostname = 'h1'")
        assert r.rows[0][0] == pytest.approx(30.0, rel=1e-5)

    def test_having_order_limit(self, cpu):
        r = cpu.sql(
            "SELECT hostname, sum(usage_user) s FROM cpu GROUP BY hostname"
            " HAVING s >= 70 ORDER BY s DESC LIMIT 2"
        )
        assert r.rows == [["h1", 120.0], ["h3", 90.0]]

    def test_order_by_desc_nulls(self, cpu):
        cpu.sql("INSERT INTO cpu (hostname, region, ts, usage_user) VALUES ('h4','x',0,NULL)")
        r = cpu.sql(
            "SELECT hostname, max(usage_user) m FROM cpu GROUP BY hostname ORDER BY m DESC"
        )
        # NULLS FIRST on DESC (pg default)
        assert r.rows[0][0] == "h4" and r.rows[0][1] is None
        assert r.rows[1] == ["h1", 70.0]

    def test_limit_offset(self, cpu):
        r = cpu.sql("SELECT DISTINCT hostname FROM cpu ORDER BY hostname LIMIT 2 OFFSET 1")
        assert r.rows == [["h2"], ["h3"]]

    def test_arithmetic_projection(self, cpu):
        r = cpu.sql(
            "SELECT usage_user + usage_system AS total FROM cpu"
            " WHERE hostname = 'h1' AND ts = 0"
        )
        assert r.rows == [[11.0]]

    def test_agg_arithmetic(self, cpu):
        r = cpu.sql("SELECT max(usage_user) - min(usage_user) FROM cpu")
        assert r.rows == [[60.0]]

    def test_case_expression(self, cpu):
        r = cpu.sql(
            "SELECT hostname, CASE WHEN max(usage_user) > 55 THEN 'hot' ELSE 'cold' END"
            " FROM cpu GROUP BY hostname ORDER BY hostname"
        )
        assert r.rows == [["h1", "hot"], ["h2", "cold"], ["h3", "hot"]]

    def test_range_align(self, cpu):
        r = cpu.sql(
            "SELECT ts, hostname, max(usage_user) RANGE '1m' FROM cpu"
            " ALIGN '1m' BY (hostname) ORDER BY hostname, ts"
        )
        assert r.rows[0] == [0, "h1", 10.0]
        assert len(r.rows) == 7

    def test_tableless(self, db):
        assert db.sql("SELECT 1").rows == [[1]]
        assert db.sql("SELECT 1 + 2 AS three").rows == [[3]]
        assert db.sql("SELECT version()").rows[0][0].startswith("greptimedb-tpu")

    def test_count_on_empty_table(self, db):
        db.sql("CREATE TABLE e (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        assert db.sql("SELECT count(*) FROM e").rows == [[0]]
        assert db.sql("SELECT * FROM e").rows == []
        r = db.sql("SELECT max(v) FROM e")
        assert r.rows == [[None]]

    def test_group_by_ordinal_and_alias(self, cpu):
        r1 = cpu.sql("SELECT region r, count(*) FROM cpu GROUP BY 1 ORDER BY r")
        r2 = cpu.sql("SELECT region r, count(*) FROM cpu GROUP BY r ORDER BY r")
        assert r1.rows == r2.rows

    def test_explain(self, cpu):
        r = cpu.sql("EXPLAIN SELECT region, count(*) FROM cpu GROUP BY region")
        assert "TpuAggregate" in r.rows[0][1]


class TestDML:
    def test_insert_nulls_and_defaults(self, cpu):
        cpu.sql("INSERT INTO cpu (hostname, region, ts) VALUES ('h8','x',5000)")
        r = cpu.sql("SELECT usage_user FROM cpu WHERE hostname = 'h8'")
        assert r.rows == [[None]]

    def test_insert_ts_string(self, db):
        db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        db.sql("INSERT INTO t VALUES ('2021-01-01 00:00:00', 1.5)")
        r = db.sql("SELECT ts, v FROM t")
        assert r.rows == [[1609459200000, 1.5]]

    def test_delete(self, cpu):
        cpu.sql("DELETE FROM cpu WHERE hostname = 'h1' AND region = 'us-east' AND ts = 0")
        assert cpu.sql("SELECT count(*) FROM cpu").rows == [[6]]
        r = cpu.sql("SELECT count(*) FROM cpu WHERE hostname = 'h1'")
        assert r.rows == [[2]]

    def test_truncate(self, cpu):
        cpu.sql("TRUNCATE TABLE cpu")
        assert cpu.sql("SELECT count(*) FROM cpu").rows == [[0]]

    def test_upsert_same_key(self, db):
        db.sql("CREATE TABLE t (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(a))")
        db.sql("INSERT INTO t VALUES ('x', 1000, 1.0)")
        db.sql("INSERT INTO t VALUES ('x', 1000, 2.0)")
        assert db.sql("SELECT v FROM t").rows == [[2.0]]


class TestPersistence:
    def test_restart_roundtrip(self, tmp_data_dir):
        db = GreptimeDB(tmp_data_dir)
        db.sql("CREATE TABLE t (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(a))")
        db.sql("INSERT INTO t VALUES ('x', 1000, 1.0), ('y', 2000, 2.0)")
        db.close()
        db2 = GreptimeDB(tmp_data_dir)
        assert db2.sql("SHOW TABLES").rows == [["t"]]
        r = db2.sql("SELECT a, v FROM t ORDER BY a")
        assert r.rows == [["x", 1.0], ["y", 2.0]]
        db2.close()


class TestErrors:
    def test_syntax_error(self, db):
        with pytest.raises(SyntaxError_):
            db.sql("SELEC 1")

    def test_unknown_column(self, db):
        db.sql("CREATE TABLE t (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        from greptimedb_tpu.errors import ColumnNotFound

        with pytest.raises(ColumnNotFound):
            db.sql("SELECT nope FROM t")

    def test_table_not_found(self, db):
        with pytest.raises(TableNotFound):
            db.sql("SELECT * FROM missing")


class TestSchemaEvolutionRegressions:
    """Review findings: mixed-schema SSTs through compaction and DROP COLUMN."""

    def test_compact_across_alter(self, db):
        db.sql("CREATE TABLE t (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(a))")
        db.sql("INSERT INTO t VALUES ('x', 1000, 1.0)")
        r = db._region_of("t")
        r.flush()
        db.sql("ALTER TABLE t ADD COLUMN w DOUBLE")
        db.sql("INSERT INTO t (a, ts, v, w) VALUES ('y', 2000, 2.0, 9.0)")
        r = db._region_of("t")
        r.flush()
        r.compact()  # pre-alter + post-alter SSTs merged
        assert len(r.sst_files) == 1
        res = db.sql("SELECT a, v, w FROM t ORDER BY a")
        assert res.rows == [["x", 1.0, None], ["y", 2.0, 9.0]]

    def test_drop_column_with_old_ssts(self, db):
        db.sql("CREATE TABLE t (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, w DOUBLE, PRIMARY KEY(a))")
        db.sql("INSERT INTO t VALUES ('x', 1000, 1.0, 5.0)")
        db._region_of("t").flush()
        db.sql("ALTER TABLE t DROP COLUMN w")
        db.sql("INSERT INTO t (a, ts, v) VALUES ('y', 2000, 2.0)")
        res = db.sql("SELECT * FROM t ORDER BY a")
        assert res.column_names == ["a", "ts", "v"]
        assert res.rows == [["x", 1000, 1.0], ["y", 2000, 2.0]]
        from greptimedb_tpu.errors import ColumnNotFound
        import pytest as _pytest
        with _pytest.raises(ColumnNotFound):
            db.sql("SELECT w FROM t")

    def test_default_value_backfill(self, db):
        db.sql("CREATE TABLE t (a STRING, ts TIMESTAMP TIME INDEX, v DOUBLE, PRIMARY KEY(a))")
        db.sql("INSERT INTO t VALUES ('x', 1000, 1.0)")
        db.sql("INSERT INTO t (a, ts) VALUES ('z', 3000)")
        res = db.sql("SELECT a, v FROM t ORDER BY a")
        assert res.rows == [["x", 1.0], ["z", None]]


class TestInformationSchema:
    def test_tables_and_columns(self, cpu):
        r = cpu.sql("SELECT table_name, engine FROM information_schema.tables"
                    " WHERE table_schema = 'public'")
        assert ["cpu", "mito"] in r.rows
        r = cpu.sql(
            "SELECT column_name, semantic_type FROM information_schema.columns"
            " WHERE table_name = 'cpu' ORDER BY ordinal_position")
        assert r.rows[0] == ["hostname", "TAG"]
        assert ["ts", "TIMESTAMP"] in r.rows

    def test_region_statistics(self, cpu):
        r = cpu.sql("SELECT region_rows FROM information_schema.region_statistics")
        assert r.rows and r.rows[0][0] == 7

    def test_use_information_schema(self, cpu):
        cpu.sql("USE information_schema")
        r = cpu.sql("SELECT count(*) FROM tables")
        assert r.rows[0][0] > 0
        cpu.sql("USE public")

    def test_misc_tables(self, cpu):
        assert cpu.sql("SELECT * FROM information_schema.build_info").num_rows == 1
        assert cpu.sql("SELECT * FROM information_schema.cluster_info").num_rows == 1
        assert cpu.sql("SELECT * FROM information_schema.schemata").num_rows >= 2
        r = cpu.sql("SELECT constraint_name FROM information_schema.key_column_usage"
                    " WHERE table_name = 'cpu'")
        flat = [x[0] for x in r.rows]
        assert "PRIMARY" in flat and "TIME INDEX" in flat

    def test_column_types_threaded(self, cpu):
        r = cpu.sql("SELECT hostname, count(*) c FROM cpu GROUP BY hostname")
        assert r.column_types == ["String", "Int64"]
        r2 = cpu.sql("SELECT hostname, max(usage_user) FROM cpu GROUP BY hostname")
        assert r2.column_types == ["String", "Float64"]
        r3 = cpu.sql("SELECT date_bin(INTERVAL '1 minute', ts) m, avg(usage_user)"
                     " FROM cpu GROUP BY m")
        assert r3.column_types == ["TimestampMillisecond", "Float64"]


    def test_qualified_query_from_information_schema_db(self, cpu):
        cpu.sql("USE information_schema")
        r = cpu.sql("SELECT count(*) FROM public.cpu")
        assert r.rows == [[7]]
        assert cpu.sql("SHOW TABLES").rows[0][0] == "build_info"
        assert ["information_schema"] in cpu.sql("SHOW DATABASES").rows
        cpu.sql("USE public")

    def test_count_col_excludes_nulls_virtual(self, cpu):
        r = cpu.sql("SELECT count(table_id) FROM information_schema.tables")
        r2 = cpu.sql("SELECT count(*) FROM information_schema.tables")
        assert r.rows[0][0] < r2.rows[0][0]  # virtual tables have NULL ids

    def test_region_peers_and_ssts(self, cpu):
        r = cpu.sql("SELECT table_name, is_leader, status FROM "
                    "information_schema.region_peers")
        assert ["cpu", "Yes", "ALIVE"] in r.rows
        cpu._region_of("cpu").flush()
        r = cpu.sql("SELECT table_name, num_rows, level FROM "
                    "information_schema.ssts WHERE table_name = 'cpu'")
        assert r.num_rows == 1 and r.rows[0][1] == 7

    def test_procedure_info(self, cpu):
        r = cpu.sql("SELECT procedure_type, status FROM "
                    "information_schema.procedure_info")
        # the fixture's CREATE TABLE itself runs as a journaled procedure
        assert ["ddl/create_table", "DONE"] in r.rows
        from greptimedb_tpu.meta.procedure import Procedure, Status

        class Noop(Procedure):
            type_name = "test_noop"

            def execute(self, ctx):
                return Status.done()

        cpu.procedures.register(Noop)
        cpu.procedures.submit(Noop())
        r = cpu.sql("SELECT procedure_type, status FROM "
                    "information_schema.procedure_info")
        assert ["test_noop", "DONE"] in r.rows

    def test_runtime_metrics(self, cpu):
        from greptimedb_tpu.utils.telemetry import REGISTRY

        REGISTRY.counter("test_info_schema_total", "x").inc(3)
        r = cpu.sql("SELECT value FROM information_schema.runtime_metrics "
                    "WHERE metric_name = 'test_info_schema_total'")
        assert r.rows and r.rows[0][0] == 3.0


class TestProcessList:
    def test_show_processlist_shows_self(self, cpu):
        r = cpu.sql("SHOW PROCESSLIST")
        assert r.num_rows == 1
        row = dict(zip(r.column_names, r.rows[0]))
        assert "SHOW PROCESSLIST" in row["Query"]
        r = cpu.sql("SELECT query FROM information_schema.process_list")
        assert r.num_rows == 1 and "process_list" in r.rows[0][0]

    def test_kill_unknown_id_errors(self, cpu):
        from greptimedb_tpu.errors import InvalidArguments

        with pytest.raises(InvalidArguments):
            cpu.sql("KILL 99999")
        with pytest.raises(InvalidArguments):
            cpu.sql("KILL 'not-a-number'")

    def test_kill_cancels_queued_statement(self, cpu):
        """KILL from another thread cancels the remaining statements of a
        multi-statement script at the next stage boundary."""
        import threading
        import time as _t

        from greptimedb_tpu.errors import Cancelled

        errs = []
        started = threading.Event()

        orig = cpu.execute_statement

        def slow_execute(stmt):
            started.set()
            _t.sleep(0.15)
            return orig(stmt)

        cpu.execute_statement = slow_execute

        def victim():
            try:
                cpu.sql("SELECT 1; SELECT 2; SELECT 3")
            except Cancelled as e:
                errs.append(e)

        th = threading.Thread(target=victim)
        th.start()
        assert started.wait(5)
        # the victim registered first → its ticket id is the smallest live id
        for _ in range(100):
            procs = cpu.processes.list()
            if procs:
                break
            _t.sleep(0.01)
        victim_id = procs[0].id
        cpu.processes.kill(victim_id)
        th.join(10)
        cpu.execute_statement = orig
        assert errs, "victim should have been cancelled"
        assert not cpu.processes.list()  # ticket deregistered

    def test_kill_statement_roundtrip(self, cpu):
        t = cpu.processes.register("SELECT sleep_forever()", "public")
        cpu.sql(f"KILL {t.id}")
        assert t.cancelled.is_set()
        cpu.processes.deregister(t)

    def test_kill_addr_form(self, cpu):
        t = cpu.processes.register("x", "public")
        cpu.sql(f"KILL 'standalone/{t.id}'")
        assert t.cancelled.is_set()
        cpu.processes.deregister(t)

    def test_kill_via_wire_session_bypasses_executor_lock(self, cpu):
        """sql_in_db (the wire-protocol entry) must run KILL without
        queueing behind the running statement it targets."""
        import threading
        import time as _t

        from greptimedb_tpu.errors import Cancelled

        errs = []
        started = threading.Event()
        orig = cpu.execute_statement

        def slow(stmt):
            started.set()
            _t.sleep(0.2)
            return orig(stmt)

        cpu.execute_statement = slow

        def victim():
            try:
                cpu.sql_in_db("SELECT 1; SELECT 2; SELECT 3", "public")
            except Cancelled as e:
                errs.append(e)

        th = threading.Thread(target=victim)
        th.start()
        assert started.wait(5)
        vid = cpu.processes.list()[0].id
        t0 = _t.perf_counter()
        r, _, _ = cpu.sql_in_db(f"KILL {vid}", "public")
        kill_s = _t.perf_counter() - t0
        th.join(10)
        cpu.execute_statement = orig
        assert errs and r.affected_rows == 1
        assert kill_s < 0.5, f"KILL queued behind victim ({kill_s:.2f}s)"

    def test_queued_wire_statement_visible_and_killable(self, cpu):
        """A wire statement blocked on the executor lock must appear in
        SHOW PROCESSLIST and die via KILL once it acquires the lock."""
        import threading
        import time as _t

        from greptimedb_tpu.errors import Cancelled

        release = threading.Event()
        holding = threading.Event()
        errs = []

        def holder():
            with cpu._lock:
                holding.set()
                release.wait(5)

        def queued_victim():
            try:
                cpu.sql_in_db("SELECT 1", "public")
            except Cancelled as e:
                errs.append(e)

        th_hold = threading.Thread(target=holder)
        th_hold.start()
        assert holding.wait(5)
        th_vic = threading.Thread(target=queued_victim)
        th_vic.start()
        # victim is queued on the lock — it must still have a live ticket
        for _ in range(200):
            procs = cpu.processes.list()
            if any("SELECT 1" in p.query for p in procs):
                break
            _t.sleep(0.01)
        vic = [p for p in procs if "SELECT 1" in p.query]
        assert vic, "queued statement invisible to processlist"
        assert cpu.processes.kill(vic[0].id)
        release.set()
        th_vic.join(10)
        th_hold.join(5)
        assert errs, "queued victim should be cancelled on lock acquisition"

    def test_show_full_tables_and_processlist(self, cpu):
        # SHOW FULL TABLES grew support in round 5 (golden 100); the
        # FULL prefix must still route PROCESSLIST correctly
        r = cpu.sql("SHOW FULL TABLES")
        assert r.column_names == ["Tables", "Table_type"]
        assert cpu.sql("SHOW FULL PROCESSLIST").num_rows == 1


class TestPartitionedTables:
    @pytest.fixture
    def ptab(self, db):
        db.sql(
            "CREATE TABLE pt (host STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE,"
            " PRIMARY KEY (host))"
            " PARTITION ON COLUMNS (host) (host < 'm', host >= 'm')"
        )
        db.sql(
            "INSERT INTO pt VALUES ('alpha', 1000, 1.0), ('zulu', 1000, 2.0),"
            " ('beta', 2000, 3.0), ('november', 2000, 4.0)"
        )
        return db

    def test_regions_created_and_routed(self, ptab):
        info = ptab.catalog.get_table("public", "pt")
        assert len(info.region_ids) == 2
        r0 = ptab.regions.regions[info.region_ids[0]]
        r1 = ptab.regions.regions[info.region_ids[1]]
        h0 = set(r0.scan_host()["host"])
        h1 = set(r1.scan_host()["host"])
        assert h0 == {"alpha", "beta"} and h1 == {"zulu", "november"}

    def test_merged_query(self, ptab):
        r = ptab.sql("SELECT host, v FROM pt ORDER BY host")
        assert r.rows == [["alpha", 1.0], ["beta", 3.0],
                          ["november", 4.0], ["zulu", 2.0]]
        r = ptab.sql("SELECT count(*), sum(v) FROM pt")
        assert r.rows == [[4, 10.0]]
        r = ptab.sql("SELECT host, max(v) FROM pt WHERE ts = 2000 GROUP BY host ORDER BY host")
        assert r.rows == [["beta", 3.0], ["november", 4.0]]

    def test_cross_partition_filter(self, ptab):
        r = ptab.sql("SELECT count(*) FROM pt WHERE host IN ('alpha', 'zulu')")
        assert r.rows == [[2]]

    def test_partition_upsert(self, ptab):
        ptab.sql("INSERT INTO pt VALUES ('zulu', 1000, 20.0)")
        r = ptab.sql("SELECT v FROM pt WHERE host = 'zulu' AND ts = 1000")
        assert r.rows == [[20.0]]

    def test_information_schema_partitions(self, ptab):
        r = ptab.sql(
            "SELECT partition_name, partition_expression FROM"
            " information_schema.partitions WHERE table_name = 'pt'"
            " ORDER BY partition_name"
        )
        assert r.rows == [["p0", "host < 'm'"], ["p1", "host >= 'm'"]]

    def test_partitioned_tql(self, ptab):
        res = ptab.sql("TQL EVAL (1, 2, '1') pt")
        hosts = {r[0] for r in res.rows}
        assert hosts == {"alpha", "beta", "november", "zulu"}

    def test_truncate_partitioned(self, ptab):
        ptab.sql("TRUNCATE TABLE pt")
        assert ptab.sql("SELECT count(*) FROM pt").rows == [[0]]

    def test_alter_partitioned_invalidates_view_cache(self, ptab):
        for rid in ptab.catalog.get_table("public", "pt").region_ids:
            ptab.regions.regions[rid].flush()
        ptab.sql("SELECT host, v FROM pt")  # populate the view cache
        ptab.sql("ALTER TABLE pt ADD COLUMN extra DOUBLE")
        r = ptab.sql("SELECT host, v, extra FROM pt ORDER BY host LIMIT 1")
        assert r.rows == [["alpha", 1.0, None]]


class TestSortedFastPath:
    @staticmethod
    def _run_query(db):
        return db.sql(
            "SELECT host, date_bin(INTERVAL '5 minute', ts) b, avg(v), max(v),"
            " count(*) FROM st GROUP BY host, b ORDER BY host, b LIMIT 3")

    def test_single_tag_groupby_uses_sorted_path(self, db, monkeypatch):
        db.sql("CREATE TABLE st (host STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (host))")
        r = db._region_of("st")
        import numpy as np
        n = 3000
        hosts = [f"h{i:03d}" for i in range(30)]
        r.write({"host": [hosts[i % 30] for i in range(n)],
                 "ts": np.arange(n) * 1000,
                 "v": np.arange(n, dtype=float)})
        table = db.cache.get(db._table_view("st"))
        assert "host" in table.sorted_tags  # precondition for the fast path
        # force the sorted kernel (CPU-gated by default) to cover it e2e
        import greptimedb_tpu.query.physical as phys
        before = dict(phys.DISPATCH_STATS)
        monkeypatch.setenv("GREPTIME_SORTED_SEGMENTS", "force")
        res = self._run_query(db)
        monkeypatch.setenv("GREPTIME_SORTED_SEGMENTS", "off")
        assert phys.DISPATCH_STATS["sorted"] > before["sorted"]  # really ran
        res2 = db.sql(  # and the scatter path for comparison

            "SELECT host, date_bin(INTERVAL '5 minute', ts) b, avg(v), max(v),"
            " count(*) FROM st GROUP BY host, b ORDER BY host, b LIMIT 3")
        assert res.rows == res2.rows
        # numpy cross-check of first group
        import numpy as np
        hs = np.array([hosts[i % 30] for i in range(n)])
        ts = np.arange(n) * 1000
        v = np.arange(n, dtype=float)
        sel = (hs == "h000") & (ts // 300000 == 0)
        assert res.rows[0][0] == "h000" and res.rows[0][1] == 0
        assert res.rows[0][2] == pytest.approx(v[sel].mean())
        assert res.rows[0][3] == v[sel].max()
        assert res.rows[0][4] == int(sel.sum())

    def test_sorted_path_with_where(self, db):
        db.sql("CREATE TABLE st2 (host STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (host))")
        r = db._region_of("st2")
        import numpy as np
        n = 1000
        r.write({"host": [f"h{i % 10}" for i in range(n)],
                 "ts": np.arange(n) * 1000, "v": np.ones(n)})
        res = db.sql("SELECT host, sum(v) FROM st2 WHERE ts >= 100000 AND ts < 200000"
                     " GROUP BY host ORDER BY host")
        total = sum(row[1] for row in res.rows)
        assert total == 100.0


class TestStringFieldRegressions:
    def test_null_string_field_query(self, db):
        db.sql("CREATE TABLE lg (ts TIMESTAMP(3) TIME INDEX, line STRING)")
        db.sql("INSERT INTO lg VALUES (1000, 'hello'), (2000, NULL)")
        r = db.sql("SELECT line FROM lg ORDER BY ts")
        assert r.rows == [["hello"], [""]]

    def test_string_field_aggregate_rejected(self, db):
        db.sql("CREATE TABLE lg2 (ts TIMESTAMP(3) TIME INDEX, line STRING)")
        db.sql("INSERT INTO lg2 VALUES (1000, 'zebra'), (2000, 'apple')")
        with pytest.raises(Unsupported):
            db.sql("SELECT max(line) FROM lg2")
        assert db.sql("SELECT count(line) FROM lg2").rows == [[2]]

    def test_sorted_minmax_tagless_timeonly(self, db, monkeypatch):
        # review regression: padding rows must not corrupt min/max on the
        # sorted path for tag-less time-only group-bys
        db.sql("CREATE TABLE nt (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        import numpy as np
        r = db._region_of("nt")
        n = 100  # pads to 128 -> 28 padding rows
        r.write({"ts": np.arange(n) * 60_000, "v": np.arange(n, dtype=float)})
        monkeypatch.setenv("GREPTIME_SORTED_SEGMENTS", "force")
        res = db.sql("SELECT date_bin(INTERVAL '30 minute', ts) b, max(v), min(v)"
                     " FROM nt GROUP BY b ORDER BY b")
        assert res.rows[-1][1] == 99.0  # last bucket max intact
        assert res.rows[0][2] == 0.0


class TestExplainAnalyze:
    def test_stage_metrics(self, cpu):
        r = cpu.sql("EXPLAIN ANALYZE SELECT hostname, avg(usage_user)"
                    " FROM cpu GROUP BY hostname")
        assert len(r.rows) == 2
        text = r.rows[1][1]
        for key in ("plan_ms", "device_exec_ms", "shape_ms", "output_rows"):
            assert key in text


class TestSlidingRange:
    def test_range_wider_than_align(self, db):
        db.sql("CREATE TABLE sr (h STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY(h))")
        db.sql("INSERT INTO sr VALUES "
               "('a', 0, 1.0), ('a', 60000, 2.0), ('a', 120000, 4.0),"
               " ('a', 180000, 8.0)")
        # 2-minute window sliding at 1-minute steps, window = [t, t+2m)
        r = db.sql("SELECT ts, h, sum(v) RANGE '2m' FROM sr ALIGN '1m'"
                   " BY (h) ORDER BY ts")
        got = {row[0]: row[2] for row in r.rows}
        assert got[0] == 3.0        # 0..2m: 1+2
        assert got[60000] == 6.0    # 1..3m: 2+4
        assert got[120000] == 12.0  # 2..4m: 4+8
        assert got[180000] == 8.0
        assert got[-60000] == 1.0   # window [-1m, 1m) catches the first point

    def test_sliding_avg_and_minmax(self, db):
        db.sql("CREATE TABLE sr2 (h STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY(h))")
        db.sql("INSERT INTO sr2 VALUES ('a', 0, 2.0), ('a', 60000, 6.0),"
               " ('b', 0, 10.0)")
        r = db.sql("SELECT ts, h, avg(v) RANGE '2m', max(v) RANGE '2m'"
                   " FROM sr2 ALIGN '1m' BY (h) ORDER BY h, ts")
        by = {(row[1], row[0]): (row[2], row[3]) for row in r.rows}
        assert by[("a", 0)] == (4.0, 6.0)
        assert by[("b", 0)] == (10.0, 10.0)

    def test_invalid_range_multiple(self, db):
        db.sql("CREATE TABLE sr3 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        with pytest.raises(Unsupported):
            db.sql("SELECT ts, sum(v) RANGE '90s' FROM sr3 ALIGN '1m'")

    def test_rangeless_agg_rejected_in_range_query(self, db):
        db.sql("CREATE TABLE sr4 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        with pytest.raises(Unsupported, match="RANGE clause"):
            db.sql("SELECT ts, sum(v) RANGE '2m', count(v) FROM sr4 ALIGN '1m'")

    def test_distinct_agg_rejected_in_sliding(self, db):
        db.sql("CREATE TABLE sr5 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        with pytest.raises(Unsupported):
            db.sql("SELECT ts, count(DISTINCT v) RANGE '2m' FROM sr5 ALIGN '1m'")


class TestCopy:
    def test_copy_parquet_roundtrip(self, cpu, tmp_path):
        path = str(tmp_path / "cpu.parquet")
        r = cpu.sql(f"COPY cpu TO '{path}' WITH (format = 'parquet')")
        assert r.affected_rows == 7
        cpu.sql("CREATE TABLE cpu2 (hostname STRING, region STRING,"
                " ts TIMESTAMP(3) TIME INDEX, usage_user DOUBLE,"
                " usage_system DOUBLE, PRIMARY KEY (hostname, region))")
        r = cpu.sql(f"COPY cpu2 FROM '{path}'")
        assert r.affected_rows == 7
        assert cpu.sql("SELECT count(*) FROM cpu2").rows == [[7]]
        a = cpu.sql("SELECT hostname, usage_user FROM cpu ORDER BY hostname, ts").rows
        b = cpu.sql("SELECT hostname, usage_user FROM cpu2 ORDER BY hostname, ts").rows
        assert a == b

    def test_copy_csv_and_json(self, cpu, tmp_path):
        for fmt in ("csv", "json"):
            path = str(tmp_path / f"cpu.{fmt}")
            r = cpu.sql(f"COPY cpu TO '{path}' WITH (format = '{fmt}')")
            assert r.affected_rows == 7
            tname = f"cpu_{fmt}"
            cpu.sql(f"CREATE TABLE {tname} (hostname STRING, region STRING,"
                    " ts TIMESTAMP(3) TIME INDEX, usage_user DOUBLE,"
                    " usage_system DOUBLE, PRIMARY KEY (hostname, region))")
            r = cpu.sql(f"COPY {tname} FROM '{path}' WITH (format = '{fmt}')")
            assert r.affected_rows == 7
            assert cpu.sql(f"SELECT count(*) FROM {tname}").rows == [[7]]

    def test_copy_bad_format(self, cpu, tmp_path):
        with pytest.raises(Unsupported):
            cpu.sql(f"COPY cpu TO '{tmp_path}/x' WITH (format = 'xml')")


class TestPgCatalog:
    def test_pg_tables_and_class(self, cpu):
        r = cpu.sql("SELECT schemaname, tablename FROM pg_catalog.pg_tables"
                    " WHERE schemaname = 'public'")
        assert ["public", "cpu"] in r.rows
        r = cpu.sql("SELECT relname FROM pg_catalog.pg_class WHERE relkind = 'r'")
        assert ["cpu"] in r.rows
        r = cpu.sql("SELECT nspname FROM pg_catalog.pg_namespace")
        flat = [x[0] for x in r.rows]
        assert "pg_catalog" in flat and "public" in flat
        r = cpu.sql("SELECT datname FROM pg_catalog.pg_database")
        assert ["public"] in r.rows

    def test_copy_from_with_null_int_and_ns_timestamps(self, db, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        db.sql("CREATE TABLE ct (ts TIMESTAMP(3) TIME INDEX, n BIGINT, v DOUBLE)")
        t = pa.table({
            "ts": pa.array([1600000000001000000, 1600000000002000000],
                           pa.timestamp("ns")),  # ns file vs ms schema
            "n": pa.array([5, None], pa.int64()),
            "v": pa.array([1.0, None]),
        })
        pq.write_table(t, str(tmp_path / "in.parquet"))
        r = db.sql(f"COPY ct FROM '{tmp_path}/in.parquet'")
        assert r.affected_rows == 2
        rows = db.sql("SELECT ts, n, v FROM ct ORDER BY ts").rows
        assert rows[0][0] == 1600000000001  # unit-cast to ms, not raw ns
        assert rows[1][2] is None  # float null survives

    def test_copy_from_triggers_flows(self, db, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        db.sql("CREATE TABLE fsrc (h STRING, ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY(h))")
        db.sql("CREATE FLOW cf SINK TO fsink AS SELECT"
               " date_bin(INTERVAL '1 minute', ts) AS minute, h, sum(v) AS s"
               " FROM fsrc GROUP BY minute, h")
        t = pa.table({"h": ["x", "x"], "ts": pa.array([1000, 2000], pa.timestamp("ms")),
                      "v": [1.0, 2.0]})
        pq.write_table(t, str(tmp_path / "f.parquet"))
        db.sql(f"COPY fsrc FROM '{tmp_path}/f.parquet'")
        assert db.sql("SELECT s FROM fsink").rows == [[3.0]]


class TestScalarFunctions:
    def test_json_functions(self, db):
        db.sql("CREATE TABLE js (ts TIMESTAMP(3) TIME INDEX, doc STRING)")
        db.sql("""INSERT INTO js VALUES (1000, '{"user": {"name": "ada", "age": 36}, "tags": ["x", "y"]}'),
                  (2000, 'not json')""")
        r = db.sql("SELECT json_get_string(doc, '$.user.name'),"
                   " json_get_int(doc, '$.user.age'),"
                   " json_get_string(doc, '$.tags[1]'),"
                   " json_path_exists(doc, '$.user') FROM js ORDER BY ts")
        assert r.rows[0] == ["ada", 36, "y", True]
        assert r.rows[1] == [None, None, None, False]

    def test_ip_and_string_functions(self, db):
        db.sql("CREATE TABLE ipt (ts TIMESTAMP(3) TIME INDEX, ip BIGINT, name STRING)")
        db.sql("INSERT INTO ipt VALUES (1000, 3232235777, '  WebServer  ')")
        r = db.sql("SELECT ipv4_num_to_string(ip), lower(trim(name)),"
                   " length(trim(name)), substr(trim(name), 1, 3) FROM ipt")
        assert r.rows == [["192.168.1.1", "webserver", 9, "Web"]]
        r = db.sql("SELECT ipv4_string_to_num('10.0.0.1')")
        assert r.rows == [[167772161]]

    def test_json_semantics_regressions(self, db):
        db.sql("CREATE TABLE js2 (ts TIMESTAMP(3) TIME INDEX, doc STRING)")
        db.sql('INSERT INTO js2 VALUES (1000, '
               '\'{"a": null, "o": {"b": 1}, "f": false, "n": 1}\')')
        r = db.sql("SELECT json_path_exists(doc, '$.a'),"
                   " json_path_exists(doc, '$.zz'),"
                   " json_get_string(doc, '$.o'),"
                   " json_get_bool(doc, '$.f'),"
                   " json_get_bool(doc, '$.n') FROM js2")
        row = r.rows[0]
        assert row[0] is True        # null value: path EXISTS
        assert row[1] is False
        assert row[2] == '{"b": 1}'  # JSON text, not python repr
        assert row[3] is False
        assert row[4] is None        # non-bool -> NULL

    def test_substr_pg_semantics(self, db):
        r = db.sql("SELECT substr('alphabet', 0, 3), substr('alphabet', 0),"
                   " substr('alphabet', 3, 2)")
        assert r.rows == [["al", "alphabet", "ph"]]


class TestTimezones:
    def test_set_time_zone_applies_to_literals(self, db):
        db.sql("CREATE TABLE tz (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        db.sql("SET time_zone = '+08:00'")
        try:
            db.sql("INSERT INTO tz VALUES ('2026-01-01 08:00:00', 1.0)")
            # 08:00 at +08:00 == midnight UTC
            r = db.sql("SELECT ts FROM tz")
            assert r.rows == [[1767225600000]]
            # WHERE literals parse in session tz too
            assert db.sql("SELECT count(*) FROM tz WHERE"
                          " ts >= '2026-01-01 07:59:00'").rows == [[1]]
            db.sql("SET time_zone = 'UTC'")
            assert db.sql("SELECT count(*) FROM tz WHERE"
                          " ts >= '2026-01-01 00:00:00'").rows == [[1]]
            assert db.sql("SELECT count(*) FROM tz WHERE"
                          " ts >= '2026-01-01 00:00:01'").rows == [[0]]
        finally:
            db.sql("SET time_zone = 'UTC'")

    def test_named_zone_and_bad_zone(self, db):
        db.sql("SET time_zone = 'Asia/Shanghai'")
        db.sql("SET time_zone = 'UTC'")
        with pytest.raises(InvalidArguments):
            db.sql("SET time_zone = 'Not/AZone'")
        # unrelated SETs are tolerated no-ops
        assert db.sql("SET sql_mode = 'ANSI'").rows == []


class TestSlowQueryRecorder:
    def test_slow_queries_recorded(self, cpu):
        cpu.slow_query_threshold_ms = 0.0001  # everything is "slow"
        try:
            cpu.sql("SELECT count(*) FROM cpu")
        finally:
            cpu.slow_query_threshold_ms = 0.0
        r = cpu.sql("SELECT query, cost_ms FROM greptime_private.slow_queries")
        assert r.num_rows >= 1
        assert "count(*)" in r.rows[0][0]
        assert r.rows[0][1] > 0
        # recording itself (and DDL) is not re-recorded
        n_before = r.num_rows
        cpu.sql("CREATE TABLE notslow (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        r2 = cpu.sql("SELECT count(*) FROM greptime_private.slow_queries")
        assert r2.rows[0][0] == n_before

    def test_disabled_by_default(self, db):
        db.sql("CREATE TABLE q (ts TIMESTAMP TIME INDEX, v DOUBLE)")
        db.sql("SELECT count(*) FROM q")
        assert not db.catalog.database_exists("greptime_private")


class TestDistinctAggregates:
    def test_count_distinct(self, db):
        db.sql("CREATE TABLE cd (host STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO cd VALUES ('a',1000,1.0),('a',2000,1.0),"
               "('a',3000,2.0),('b',4000,1.0),('b',5000,NULL)")
        assert db.sql("SELECT host, count(DISTINCT v) FROM cd GROUP BY host"
                      " ORDER BY host").rows == [["a", 2], ["b", 1]]
        assert db.sql("SELECT count(DISTINCT host) FROM cd").rows == [[2]]
        assert db.sql("SELECT count(DISTINCT v) FROM cd").rows == [[2]]
        # mixed with plain aggs (must not join the batched wide pass)
        assert db.sql(
            "SELECT host, count(v), count(DISTINCT v), sum(v) FROM cd "
            "GROUP BY host ORDER BY host"
        ).rows == [["a", 3, 2, 4.0], ["b", 1, 1, 1.0]]

    def test_distinct_only_for_count(self, db):
        db.sql("CREATE TABLE cd2 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        db.sql("INSERT INTO cd2 VALUES (1000, 1.0)")
        with pytest.raises(Unsupported):
            db.sql("SELECT sum(DISTINCT v) FROM cd2")


class TestUnion:
    def test_union_dedup_and_all(self, db):
        db.sql("CREATE TABLE u1 (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        db.sql("CREATE TABLE u2 (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO u1 VALUES ('x',1000,1.0),('y',2000,2.0)")
        db.sql("INSERT INTO u2 VALUES ('x',1000,1.0),('z',3000,3.0)")
        assert db.sql("SELECT h, v FROM u1 UNION SELECT h, v FROM u2 "
                      "ORDER BY h").rows == [["x", 1.0], ["y", 2.0],
                                             ["z", 3.0]]
        assert db.sql("SELECT h, v FROM u1 UNION ALL SELECT h, v FROM u2 "
                      "ORDER BY v DESC LIMIT 2").rows == [["z", 3.0],
                                                          ["y", 2.0]]
        assert db.sql("SELECT count(*) FROM u1 UNION ALL "
                      "SELECT count(*) FROM u2").rows == [[2], [2]]

    def test_union_column_mismatch(self, db):
        db.sql("CREATE TABLE u3 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        db.sql("INSERT INTO u3 VALUES (1000, 1.0)")
        with pytest.raises(PlanError):
            db.sql("SELECT v FROM u3 UNION SELECT v, ts FROM u3")


class TestSubqueries:
    def test_scalar_and_in_subqueries(self, db):
        db.sql("CREATE TABLE sq (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO sq VALUES ('a',1000,1.0),('b',2000,5.0),"
               "('c',3000,3.0)")
        assert db.sql("SELECT h, v FROM sq WHERE v > (SELECT avg(v) FROM sq)"
                      " ORDER BY h").rows == [["b", 5.0]]
        assert db.sql("SELECT h FROM sq WHERE h IN (SELECT h FROM sq "
                      "WHERE v >= 3.0) ORDER BY h").rows == [["b"], ["c"]]
        assert db.sql("SELECT h FROM sq WHERE h NOT IN (SELECT h FROM sq "
                      "WHERE v >= 3.0) ORDER BY h").rows == [["a"]]
        assert db.sql("SELECT (SELECT max(v) FROM sq) AS mx").rows == [[5.0]]
        # empty IN subquery: nothing matches; NOT IN matches all
        assert db.sql("SELECT count(*) FROM sq WHERE h IN "
                      "(SELECT h FROM sq WHERE v > 99)").rows == [[0]]
        assert db.sql("SELECT count(*) FROM sq WHERE h NOT IN "
                      "(SELECT h FROM sq WHERE v > 99)").rows == [[3]]

    def test_scalar_subquery_multi_row_errors(self, db):
        db.sql("CREATE TABLE sq2 (ts TIMESTAMP(3) TIME INDEX, v DOUBLE)")
        db.sql("INSERT INTO sq2 VALUES (1000,1.0),(2000,2.0)")
        with pytest.raises(PlanError):
            db.sql("SELECT v FROM sq2 WHERE v = (SELECT v FROM sq2)")


class TestJoins:
    @pytest.fixture
    def jdb(self, db):
        db.sql("CREATE TABLE metrics (host STRING, ts TIMESTAMP(3) "
               "TIME INDEX, cpu DOUBLE, PRIMARY KEY (host))")
        db.sql("CREATE TABLE meta (host STRING, ts TIMESTAMP(3) TIME INDEX,"
               " dc STRING, weight DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO metrics VALUES ('a',1000,10.0),('a',2000,20.0),"
               "('b',1000,30.0),('c',1000,40.0)")
        db.sql("INSERT INTO meta VALUES ('a',0,'us',1.0),('b',0,'eu',2.0)")
        return db

    def test_inner_join_groupby_device_agg(self, jdb):
        r = jdb.sql("SELECT m.host, meta.dc, sum(m.cpu) FROM metrics m "
                    "JOIN meta ON m.host = meta.host "
                    "GROUP BY m.host, meta.dc ORDER BY m.host")
        assert r.rows == [["a", "us", 30.0], ["b", "eu", 30.0]]

    def test_left_join_misses(self, jdb):
        r = jdb.sql("SELECT m.host, meta.dc, count(*) FROM metrics m "
                    "LEFT JOIN meta ON m.host = meta.host "
                    "GROUP BY m.host, meta.dc ORDER BY m.host")
        assert r.rows == [["a", "us", 2], ["b", "eu", 1], ["c", "", 1]]

    def test_join_projection_and_where(self, jdb):
        r = jdb.sql("SELECT m.host, m.cpu, meta.weight FROM metrics m "
                    "JOIN meta ON m.host = meta.host "
                    "ORDER BY m.host, m.cpu")
        assert r.rows == [["a", 10.0, 1.0], ["a", 20.0, 1.0],
                          ["b", 30.0, 2.0]]
        assert jdb.sql("SELECT count(*) FROM metrics m JOIN meta "
                       "ON m.host = meta.host WHERE m.host = 'a'"
                       ).rows == [[2]]

    def test_join_agg_by_right_field(self, jdb):
        r = jdb.sql("SELECT meta.dc, avg(m.cpu) FROM metrics m JOIN meta "
                    "ON m.host = meta.host GROUP BY meta.dc ORDER BY meta.dc")
        assert r.rows == [["eu", 30.0], ["us", 15.0]]

    def test_join_expression_on_both_sides(self, jdb):
        r = jdb.sql("SELECT m.host, m.cpu * meta.weight AS wcpu "
                    "FROM metrics m JOIN meta ON m.host = meta.host "
                    "ORDER BY m.host, wcpu")
        assert r.rows == [["a", 10.0], ["a", 20.0], ["b", 60.0]]

    def test_join_errors(self, jdb):
        with pytest.raises(PlanError):
            jdb.sql("SELECT 1 FROM metrics m JOIN meta m "
                    "ON m.host = m.host")  # duplicate alias
        with pytest.raises(Unsupported):
            jdb.sql("SELECT 1 FROM metrics m JOIN meta "
                    "ON m.cpu > meta.weight")  # non-equi


class TestStringFieldGroupBy:
    def test_string_field_key_decoded(self, db):
        """Regression: GROUP BY over a string FIELD must decode the ad-hoc
        dictionary codes, not leak them."""
        db.sql("CREATE TABLE lg3 (ts TIMESTAMP(3) TIME INDEX, "
               "level STRING, n DOUBLE)")
        db.sql("INSERT INTO lg3 VALUES (1000,'info',1.0),(2000,'warn',2.0),"
               "(3000,'info',3.0)")
        r = db.sql("SELECT level, count(*), sum(n) FROM lg3 "
                   "GROUP BY level ORDER BY level")
        assert r.rows == [["info", 2, 4.0], ["warn", 1, 2.0]]


class TestJoinReviewRegressions:
    @pytest.fixture
    def jdb(self, db):
        db.sql("CREATE TABLE metrics (host STRING, ts TIMESTAMP(3) "
               "TIME INDEX, cpu DOUBLE, PRIMARY KEY (host))")
        db.sql("CREATE TABLE meta (host STRING, ts TIMESTAMP(3) TIME INDEX,"
               " dc STRING, weight DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO metrics VALUES ('a',1000,10.0),('a',2000,20.0),"
               "('b',1000,30.0),('c',1000,40.0)")
        db.sql("INSERT INTO meta VALUES ('a',0,'us',1.0),('b',0,'eu',2.0)")
        return db

    def test_join_case_expression(self, jdb):
        """Regression: CASE WHEN arms (tuple-of-tuples) must be rewritten."""
        r = jdb.sql(
            "SELECT m.host, CASE WHEN m.host = 'a' THEN 1 ELSE 0 END "
            "AS kind FROM metrics m "
            "JOIN meta ON m.host = meta.host GROUP BY m.host, kind "
            "ORDER BY m.host"
        )
        assert [row[1] for row in r.rows] == [1, 0]

    def test_subquery_inside_case(self, jdb):
        r = jdb.sql(
            "SELECT host, CASE WHEN cpu > (SELECT avg(cpu) FROM metrics) "
            "THEN 'hot' ELSE 'cool' END AS t FROM metrics ORDER BY host, cpu"
        )
        assert [row[1] for row in r.rows] == ["cool", "cool", "hot", "hot"]

    def test_multi_column_count_distinct_rejected(self, jdb):
        with pytest.raises(Unsupported):
            jdb.sql("SELECT count(DISTINCT host, cpu) FROM metrics")


class TestVectorSearch:
    @pytest.fixture
    def vdb(self, db):
        db.sql("CREATE TABLE docs (id STRING, ts TIMESTAMP(3) TIME INDEX, "
               "emb VECTOR(3), PRIMARY KEY (id))")
        db.sql("INSERT INTO docs VALUES "
               "('d1', 1000, '[1.0, 0.0, 0.0]'), "
               "('d2', 2000, '[0.0, 1.0, 0.0]'), "
               "('d3', 3000, '[0.7, 0.7, 0.0]')")
        return db

    def test_cos_topk(self, vdb):
        r = vdb.sql("SELECT id, vec_cos_distance(emb, '[1.0,0.0,0.0]') AS d "
                    "FROM docs ORDER BY d LIMIT 2")
        assert [x[0] for x in r.rows] == ["d1", "d3"]
        assert r.rows[0][1] == pytest.approx(0.0, abs=1e-6)

    def test_l2_and_dot(self, vdb):
        r = vdb.sql("SELECT id, vec_l2sq_distance(emb, '[1.0,0.0,0.0]') AS d"
                    " FROM docs ORDER BY d")
        assert [x[0] for x in r.rows] == ["d1", "d3", "d2"]
        r2 = vdb.sql("SELECT id FROM docs "
                     "ORDER BY vec_dot_product(emb, '[0.0,2.0,0.0]') DESC "
                     "LIMIT 1")
        assert r2.rows == [["d2"]]

    def test_vector_where_device_path(self, vdb):
        assert vdb.sql(
            "SELECT count(*) FROM docs "
            "WHERE vec_l2sq_distance(emb, '[1.0,0.0,0.0]') < 0.6"
        ).rows == [[2]]

    def test_vector_survives_flush_reopen(self, vdb, tmp_path):
        vdb._region_of("docs").flush()
        r = vdb.sql("SELECT id FROM docs "
                    "ORDER BY vec_cos_distance(emb, '[0.0,1.0,0.0]') LIMIT 1")
        assert r.rows == [["d2"]]

    def test_bad_literal_errors(self, vdb):
        with pytest.raises(PlanError):
            vdb.sql("SELECT vec_cos_distance(emb, 'nope') FROM docs")


class TestFullTextSearch:
    @pytest.fixture
    def ldb(self, db):
        db.sql("CREATE TABLE logs (app STRING, ts TIMESTAMP(3) TIME INDEX, "
               "line STRING, PRIMARY KEY (app))")
        db.sql("INSERT INTO logs VALUES "
               "('web', 1000, 'GET /api 200 OK'), "
               "('web', 2000, 'connection TIMEOUT to db'), "
               "('web', 3000, 'Error: timeout waiting for lock')")
        return db

    def test_matches_and_matches_term(self, ldb):
        assert ldb.sql("SELECT ts FROM logs WHERE matches(line, 'timeout') "
                       "ORDER BY ts").rows == [[2000], [3000]]
        # AND semantics across tokens, case-insensitive
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches(line, 'timeout error')").rows == [[1]]
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches_term(line, 'OK')").rows == [[1]]
        # substring of a token is NOT a token match
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches_term(line, 'time')").rows == [[0]]

    def test_matches_in_aggregate_query(self, ldb):
        r = ldb.sql("SELECT app, count(*) FROM logs "
                    "WHERE matches(line, 'timeout') GROUP BY app")
        assert r.rows == [["web", 2]]

    def test_logquery_match_prunes_files(self, tmp_data_dir_unused=None):
        from greptimedb_tpu.servers.logquery import execute_log_query
        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB()
        db.sql("CREATE TABLE lg (app STRING, ts TIMESTAMP(3) TIME INDEX, "
               "line STRING, PRIMARY KEY (app))")
        r = db._region_of("lg")
        r.write({"app": ["a"] * 2, "ts": [1000, 2000],
                 "line": ["alpha beta", "gamma delta"]})
        r.flush()
        r.write({"app": ["a"] * 2, "ts": [3000, 4000],
                 "line": ["epsilon zeta", "eta theta"]})
        r.flush()

        import greptimedb_tpu.storage.region as regmod

        reads = []
        real_read = regmod.read_sst

        def counting(store, meta, *a, **k):
            reads.append(meta.file_id)
            return real_read(store, meta, *a, **k)

        regmod.read_sst = counting
        try:
            out = execute_log_query(db, {
                "table": {"table": "lg"},
                "filters": [{"column": "line",
                             "filters": [{"match": "epsilon"}]}],
            })
            assert len(out.rows) == 1
            assert len(reads) == 1  # first SST pruned by token set
        finally:
            regmod.read_sst = real_read
        db.close()

    def test_ft_kernel_invalidates_after_insert(self, ldb):
        """Regression: kernels baking fulltext hit-vectors must not serve
        stale results after new rows change the dictionary."""
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches(line, 'timeout')").rows == [[2]]
        ldb.sql("INSERT INTO logs VALUES ('web', 4000, 'another timeout')")
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches(line, 'timeout')").rows == [[3]]

    def test_matches_term_with_punctuation(self, ldb):
        ldb.sql("INSERT INTO logs VALUES ('web', 5000, 'upgraded to v1.0 ok')")
        assert ldb.sql("SELECT ts FROM logs "
                       "WHERE matches_term(line, 'v1.0')").rows == [[5000]]
        # empty-token query matches nothing, not everything
        assert ldb.sql("SELECT count(*) FROM logs "
                       "WHERE matches(line, '!!!')").rows == [[0]]

    def test_deleted_rows_not_resurrected_by_token_pruning(self):
        from greptimedb_tpu.servers.logquery import execute_log_query
        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB()
        db.sql("CREATE TABLE dl (app STRING, ts TIMESTAMP(3) TIME INDEX, "
               "line STRING, PRIMARY KEY (app))")
        r = db._region_of("dl")
        r.write({"app": ["a"], "ts": [1000], "line": ["epsilon zeta"]})
        r.flush()
        db.sql("DELETE FROM dl WHERE app = 'a' AND ts = 1000")
        r.flush()  # tombstone SST (no tokens for 'epsilon')
        out = execute_log_query(db, {
            "table": {"table": "dl"},
            "filters": [{"column": "line",
                         "filters": [{"match": "epsilon"}]}],
        })
        assert len(out.rows) == 0  # not resurrected
        db.close()


class TestExistsSubqueries:
    """[NOT] EXISTS with equality decorrelation (reference sqlness
    subquery cases under tests/cases/standalone/common/select/)."""

    @pytest.fixture
    def db2(self, tmp_path):
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB(str(tmp_path / "ex"))
        d.sql("CREATE TABLE hosts (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "up DOUBLE, PRIMARY KEY (h))")
        d.sql("CREATE TABLE alerts (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "sev DOUBLE, PRIMARY KEY (h))")
        d.sql("INSERT INTO hosts VALUES ('a',1000,1.0),('b',1000,1.0),"
              "('c',1000,0.0)")
        d.sql("INSERT INTO alerts VALUES ('a',1000,3.0),('c',2000,5.0)")
        yield d
        d.close()

    def test_correlated_exists(self, db2):
        r = db2.sql("SELECT h FROM hosts WHERE EXISTS (SELECT 1 FROM "
                    "alerts WHERE alerts.h = hosts.h) ORDER BY h")
        assert r.rows == [["a"], ["c"]]

    def test_correlated_not_exists(self, db2):
        r = db2.sql("SELECT h FROM hosts WHERE NOT EXISTS (SELECT 1 FROM "
                    "alerts WHERE alerts.h = hosts.h) ORDER BY h")
        assert r.rows == [["b"]]

    def test_correlated_exists_extra_predicate(self, db2):
        r = db2.sql("SELECT h FROM hosts WHERE EXISTS (SELECT 1 FROM "
                    "alerts WHERE alerts.h = hosts.h AND sev > 4)")
        assert r.rows == [["c"]]

    def test_uncorrelated_exists(self, db2):
        assert db2.sql("SELECT count(*) FROM hosts WHERE EXISTS "
                       "(SELECT 1 FROM alerts)").rows == [[3]]
        assert db2.sql("SELECT count(*) FROM hosts WHERE EXISTS "
                       "(SELECT 1 FROM alerts WHERE sev > 99)").rows == [[0]]
        assert db2.sql("SELECT count(*) FROM hosts WHERE NOT EXISTS "
                       "(SELECT 1 FROM alerts WHERE sev > 99)").rows == [[3]]

    def test_exists_combined_with_predicate(self, db2):
        r = db2.sql("SELECT h FROM hosts WHERE up > 0 AND EXISTS "
                    "(SELECT 1 FROM alerts WHERE alerts.h = hosts.h)")
        assert r.rows == [["a"]]

    def test_exists_unsupported_shapes_refused(self, db2):
        from greptimedb_tpu.errors import Unsupported

        # outer reference outside the equality correlation
        with pytest.raises(Unsupported):
            db2.sql("SELECT h FROM hosts WHERE EXISTS (SELECT 1 FROM "
                    "alerts WHERE alerts.h = hosts.h AND "
                    "alerts.ts > hosts.ts)")
        # aggregate subquery (always one row -> EXISTS always true)
        with pytest.raises(Unsupported):
            db2.sql("SELECT h FROM hosts WHERE EXISTS (SELECT max(sev) "
                    "FROM alerts WHERE alerts.h = hosts.h)")
        # LIMIT inside correlated EXISTS
        with pytest.raises(Unsupported):
            db2.sql("SELECT h FROM hosts WHERE EXISTS (SELECT 1 FROM "
                    "alerts WHERE alerts.h = hosts.h LIMIT 0)")


class TestMultiKeyExists:
    """Multi-equality correlated EXISTS → tuple membership (round-4
    verdict item 6; the reference reaches the same semantics through
    DataFusion's semi-join decorrelation, src/query/src/planner.rs)."""

    @pytest.fixture
    def db3(self, tmp_path):
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB(str(tmp_path / "mk"))
        d.sql("CREATE TABLE pods (h STRING, svc STRING, ts TIMESTAMP(3) "
              "TIME INDEX, up DOUBLE, PRIMARY KEY (h, svc))")
        d.sql("CREATE TABLE incidents (h STRING, svc STRING, ts "
              "TIMESTAMP(3) TIME INDEX, sev DOUBLE, PRIMARY KEY (h, svc))")
        d.sql("INSERT INTO pods VALUES ('a','web',1000,1.0),"
              "('a','db',1000,1.0),('b','web',1000,1.0),('c','db',1000,1.0)")
        d.sql("INSERT INTO incidents VALUES ('a','web',1000,3.0),"
              "('c','db',2000,5.0),('b','db',2000,1.0)")
        yield d
        d.close()

    def test_two_key_exists(self, db3):
        r = db3.sql(
            "SELECT h, svc FROM pods WHERE EXISTS (SELECT 1 FROM incidents"
            " WHERE incidents.h = pods.h AND incidents.svc = pods.svc)"
            " ORDER BY h")
        assert r.rows == [["a", "web"], ["c", "db"]]

    def test_two_key_not_exists(self, db3):
        r = db3.sql(
            "SELECT h, svc FROM pods WHERE NOT EXISTS (SELECT 1 FROM "
            "incidents WHERE incidents.h = pods.h AND "
            "incidents.svc = pods.svc) ORDER BY h, svc")
        assert r.rows == [["a", "db"], ["b", "web"]]

    def test_two_key_exists_with_residual_predicate(self, db3):
        r = db3.sql(
            "SELECT h, svc FROM pods WHERE EXISTS (SELECT 1 FROM incidents"
            " WHERE incidents.h = pods.h AND incidents.svc = pods.svc"
            " AND sev > 4) ORDER BY h")
        assert r.rows == [["c", "db"]]

    def test_mixed_key_types(self, tmp_path):
        # one tag key + one numeric key in the correlation
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB(str(tmp_path / "mx"))
        d.sql("CREATE TABLE ev (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "code DOUBLE, PRIMARY KEY (h))")
        d.sql("CREATE TABLE allow (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "code DOUBLE, PRIMARY KEY (h))")
        d.sql("INSERT INTO ev VALUES ('a',1000,1.0),('a',2000,2.0),"
              "('b',1000,1.0)")
        d.sql("INSERT INTO allow VALUES ('a',1,1.0),('b',1,2.0)")
        r = d.sql("SELECT h, code FROM ev WHERE EXISTS (SELECT 1 FROM "
                  "allow WHERE allow.h = ev.h AND allow.code = ev.code) "
                  "ORDER BY h, code")
        assert r.rows == [["a", 1.0]]
        d.close()

    def test_grid_path_with_field_key(self, tmp_path):
        """Review regression: TupleIn's referenced columns must reach the
        planner (a vacuously tag-only WHERE crashed the grid executor
        with KeyError on the field column)."""
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB(str(tmp_path / "gr"))
        d.sql("CREATE TABLE ev (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "code DOUBLE, up DOUBLE, PRIMARY KEY (h))")
        d.sql("CREATE TABLE allow (h STRING, ts TIMESTAMP(3) TIME INDEX, "
              "code DOUBLE, PRIMARY KEY (h))")
        t0 = 1700000000000
        d.sql("INSERT INTO ev VALUES " + ",".join(
            f"('h{i % 4}',{t0 + i * 1000},{i % 3},{i})" for i in range(240)))
        d.sql("INSERT INTO allow VALUES ('h0',1,0.0),('h1',1,1.0)")
        d._region_of("ev").flush()
        r = d.sql("SELECT h, count(*) FROM ev WHERE EXISTS (SELECT 1 FROM"
                  " allow WHERE allow.h = ev.h AND allow.code = ev.code)"
                  " GROUP BY h ORDER BY h")
        want = {}
        allow = {("h0", 0.0), ("h1", 1.0)}
        for i in range(240):
            k = (f"h{i % 4}", float(i % 3))
            if k in allow:
                want[k[0]] = want.get(k[0], 0) + 1
        assert {row[0]: row[1] for row in r.rows} == want
        d.close()

    def test_ns_timestamp_keys_exact(self, tmp_path):
        """Review regression: int64 keys above 2^53 (ns timestamps) must
        compare exactly — a float64 downcast collapsed adjacent ns."""
        from greptimedb_tpu.standalone import GreptimeDB

        d = GreptimeDB(str(tmp_path / "ns"))
        d.sql("CREATE TABLE ev (h STRING, ts TIMESTAMP(9) TIME INDEX, "
              "up DOUBLE, PRIMARY KEY (h))")
        d.sql("CREATE TABLE al (h STRING, ts TIMESTAMP(9) TIME INDEX, "
              "up DOUBLE, PRIMARY KEY (h))")
        base = 1600000000000000000
        d.sql(f"INSERT INTO ev VALUES ('a',{base},1.0),"
              f"('a',{base + 100},2.0)")
        d.sql(f"INSERT INTO al VALUES ('a',{base},9.0)")
        r = d.sql("SELECT h, up FROM ev WHERE EXISTS (SELECT 1 FROM al "
                  "WHERE al.h = ev.h AND al.ts = ev.ts)")
        assert r.rows == [["a", 1.0]]
        d.close()

    def test_refused_shapes_still_loud(self, db3):
        from greptimedb_tpu.errors import Unsupported

        # non-equality outer reference stays refused even with two
        # equality correlations present
        with pytest.raises(Unsupported):
            db3.sql(
                "SELECT h FROM pods WHERE EXISTS (SELECT 1 FROM incidents"
                " WHERE incidents.h = pods.h AND incidents.svc = pods.svc"
                " AND incidents.ts > pods.ts)")


class TestOuterJoins:
    """RIGHT = mirrored LEFT, FULL = LEFT ∪ unmatched right (round-4
    verdict item 6; reference reaches these via DataFusion's join
    surface, src/query/src/datafusion.rs:141)."""

    @pytest.fixture
    def jdb(self, db):
        db.sql("CREATE TABLE metrics (host STRING, ts TIMESTAMP(3) "
               "TIME INDEX, cpu DOUBLE, PRIMARY KEY (host))")
        db.sql("CREATE TABLE meta (host STRING, ts TIMESTAMP(3) TIME INDEX,"
               " dc STRING, weight DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO metrics VALUES ('a',1000,10.0),('a',2000,20.0),"
               "('b',1000,30.0),('c',1000,40.0)")
        db.sql("INSERT INTO meta VALUES ('a',0,'us',1.0),('b',0,'eu',2.0),"
               "('z',0,'ap',9.0)")
        return db

    def test_right_join(self, jdb):
        r = jdb.sql("SELECT m.host, meta.dc, count(*) FROM metrics m "
                    "RIGHT JOIN meta ON m.host = meta.host "
                    "GROUP BY m.host, meta.dc ORDER BY meta.dc")
        # 'z' has no metrics rows: left side NULL-fills ("" for strings)
        assert r.rows == [["", "ap", 1], ["b", "eu", 1], ["a", "us", 2]]

    def test_full_join(self, jdb):
        r = jdb.sql("SELECT m.host, meta.dc, count(*) FROM metrics m "
                    "FULL JOIN meta ON m.host = meta.host "
                    "GROUP BY m.host, meta.dc ORDER BY m.host, meta.dc")
        # unmatched left 'c' AND unmatched right 'z' both survive
        assert r.rows == [["", "ap", 1], ["a", "us", 2], ["b", "eu", 1],
                          ["c", "", 1]]

    def test_full_outer_spelling_and_values(self, jdb):
        r = jdb.sql("SELECT m.cpu, meta.weight FROM metrics m "
                    "FULL OUTER JOIN meta ON m.host = meta.host "
                    "ORDER BY m.host, meta.dc")
        vals = {(row[0], row[1]) for row in r.rows}
        # right-miss row ('c'): weight NaN→None; left-miss row ('z'):
        # cpu NaN→None
        assert (40.0, None) in vals and (None, 9.0) in vals


def test_matches_score_and_cjk(tmp_path):
    from greptimedb_tpu.standalone import GreptimeDB

    db = GreptimeDB(str(tmp_path / "ft"))
    db.sql("CREATE TABLE logs (svc STRING, ts TIMESTAMP(3) TIME INDEX, "
           "msg STRING, PRIMARY KEY (svc)) WITH (append_mode='true')")
    db.sql("INSERT INTO logs VALUES "
           "('a',1,'database error connecting error'),"
           "('a',2,'all good here'),('a',3,'one error only'),"
           "('a',4,'数据库连接失败')")
    r = db.sql("SELECT msg, matches_score(msg, 'error') AS s FROM logs "
               "WHERE matches(msg, 'error') ORDER BY s DESC")
    assert [row[0] for row in r.rows] == [
        "database error connecting error", "one error only"]
    assert r.rows[0][1] > r.rows[1][1] > 0
    # CJK bigram tokenization (dictionary-free jieba analog)
    assert db.sql("SELECT msg FROM logs WHERE matches(msg, '数据库')"
                  ).rows == [["数据库连接失败"]]
    assert db.sql("SELECT count(*) FROM logs WHERE matches(msg, '失败')"
                  ).rows == [[1]]
    db.close()


class TestZeroRowGlobalAggregates:
    """SQL: a global aggregate over zero matched rows returns exactly one
    row with count()=0 and every other aggregate NULL — including SUM
    (round-5 review fix: float paths returned 0.0, int paths 0) and on
    both segment-reduce implementations."""

    @pytest.fixture
    def db(self):
        from greptimedb_tpu.standalone import GreptimeDB

        db = GreptimeDB()
        db.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "vi BIGINT, vf DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO t VALUES ('a', 1000, 5, 1.5), "
               "('b', 2000, 7, 2.5)")
        yield db
        db.close()

    def test_scatter_path(self, db):
        r = db.sql("SELECT count(*), sum(vi), sum(vf), min(vi), max(vi), "
                   "avg(vf) FROM t WHERE vf > 100")
        assert r.rows == [[0, None, None, None, None, None]]

    def test_nonempty_unchanged(self, db):
        r = db.sql("SELECT count(*), sum(vi), sum(vf) FROM t")
        assert r.rows == [[2, 12, 4.0]]

    def test_sorted_segments_path(self, db, monkeypatch):
        monkeypatch.setenv("GREPTIME_SORTED_SEGMENTS", "force")
        r = db.sql("SELECT sum(vf), count(*) FROM t WHERE vf > 100")
        assert r.rows == [[None, 0]]


class TestOptimizerRules:
    """Logical optimizer pass list (round-4 verdict item 5; reference
    src/query/src/optimizer/ — constant_term.rs, type_conversion.rs).
    Rules rewrite the AST before planning and EXPLAIN shows which
    applied; results must be unchanged (plan changes, not answers)."""

    @pytest.fixture
    def odb(self, db):
        db.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO t VALUES ('a',1000,1.0),('a',2000,2.0),"
               "('b',3000,3.0)")
        return db

    def test_constant_fold_where(self, odb):
        r = odb.sql("EXPLAIN SELECT h, v FROM t WHERE v > 1 + 1")
        plan = r.rows[0][1]
        assert "constant_fold" in plan and "1 + 1" not in plan
        res = odb.sql("SELECT h, v FROM t WHERE v > 1 + 1 ORDER BY v")
        assert res.rows == [["b", 3.0]]

    def test_simplify_true_and(self, odb):
        r = odb.sql("EXPLAIN SELECT h FROM t WHERE 1 = 1 AND h = 'a'")
        plan = r.rows[0][1]
        assert "simplify_predicates" in plan
        # the TRUE conjunct is gone from the filter line
        assert "1 = 1" not in plan
        res = odb.sql("SELECT count(*) FROM t WHERE 1 = 1 AND h = 'a'")
        assert res.rows == [[2]]

    def test_where_false_folds_to_empty(self, odb):
        res = odb.sql("SELECT h FROM t WHERE 1 = 2")
        assert res.rows == []
        res = odb.sql("SELECT count(*) FROM t WHERE 1 = 2 OR h = 'b'")
        assert res.rows == [[1]]

    def test_coerce_time_literals_enables_pushdown(self, odb):
        r = odb.sql("EXPLAIN SELECT h FROM t "
                    "WHERE ts >= '1970-01-01 00:00:01'")
        plan = r.rows[0][1]
        assert "coerce_time_literals" in plan
        # the coerced bound reaches the range extractor
        assert "time_range_pushdown" in plan
        assert "time in [1000," in plan
        res = odb.sql("SELECT h, v FROM t "
                      "WHERE ts >= '1970-01-01 00:00:02' ORDER BY v")
        assert res.rows == [["a", 2.0], ["b", 3.0]]

    def test_not_comparison_folds(self, odb):
        r = odb.sql("EXPLAIN SELECT h FROM t WHERE NOT (v <= 1)")
        plan = r.rows[0][1]
        assert "fold_not_comparisons" in plan
        res = odb.sql("SELECT h, v FROM t WHERE NOT (v <= 1) ORDER BY v")
        assert res.rows == [["a", 2.0], ["b", 3.0]]

    def test_pure_fn_folding(self, odb):
        res = odb.sql("SELECT h FROM t WHERE v >= power(2, 1) ORDER BY h")
        assert [row[0] for row in res.rows] == ["a", "b"]

    def test_rules_preserve_group_by_alias_matching(self, odb):
        # folded items must not break GROUP BY text matching
        res = odb.sql("SELECT h, count(*), 1 + 1 AS two FROM t "
                      "GROUP BY h, two ORDER BY h")
        assert res.rows == [["a", 2, 2], ["b", 1, 2]]


class TestVectorScaleGuard:
    def test_distinct_bound_enforced(self, db, monkeypatch):
        """Round-4 verdict weak 8: exact search fails LOUDLY past the
        distinct-vector bound instead of degrading silently."""
        from greptimedb_tpu.errors import ResourcesExhausted

        monkeypatch.setenv("GREPTIME_VECTOR_MAX_DISTINCT", "2")
        db.sql("CREATE TABLE vg (id STRING, ts TIMESTAMP(3) TIME INDEX, "
               "emb VECTOR(2), PRIMARY KEY (id))")
        db.sql("INSERT INTO vg VALUES ('a',1000,'[1,0]'),"
               "('b',2000,'[0,1]'),('c',3000,'[1,1]')")
        with pytest.raises(ResourcesExhausted, match="distinct vectors"):
            db.sql("SELECT id FROM vg ORDER BY "
                   "vec_cos_distance(emb, '[1,0]') LIMIT 1")
        # within the bound: works
        monkeypatch.setenv("GREPTIME_VECTOR_MAX_DISTINCT", "100")
        r = db.sql("SELECT id FROM vg ORDER BY "
                   "vec_cos_distance(emb, '[1,0]') LIMIT 1")
        assert r.rows == [["a"]]


class TestJoinPredicatePushdown:
    """Single-side WHERE conjuncts pre-filter the scans before host
    matching (reference push_down_filter).  NULL-satisfiable predicates
    must NOT push into a NULL-producing outer-join side (anti-join)."""

    @pytest.fixture
    def jdb(self, db):
        db.sql("CREATE TABLE m2 (host STRING, ts TIMESTAMP(3) TIME INDEX,"
               " cpu DOUBLE, PRIMARY KEY (host))")
        db.sql("CREATE TABLE meta2 (host STRING, ts TIMESTAMP(3) "
               "TIME INDEX, dc STRING, w DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO m2 VALUES ('a',1000,10.0),('a',2000,20.0),"
               "('b',1000,30.0),('c',1000,40.0)")
        db.sql("INSERT INTO meta2 VALUES ('a',0,'us',1.0),"
               "('b',0,'eu',2.0)")
        return db

    def test_inner_pushdown_same_result(self, jdb):
        r = jdb.sql("SELECT m2.host, meta2.dc FROM m2 JOIN meta2 "
                    "ON m2.host = meta2.host "
                    "WHERE m2.cpu > 15 AND meta2.dc = 'eu' ORDER BY m2.host")
        assert r.rows == [["b", "eu"]]

    def test_left_join_right_side_predicate(self, jdb):
        # null-rejecting right predicate pushes; (l, NULL) rows then fail
        # the re-applied WHERE exactly like unmatched-and-filtered rows
        r = jdb.sql("SELECT m2.host FROM m2 LEFT JOIN meta2 "
                    "ON m2.host = meta2.host WHERE meta2.w >= 2 "
                    "ORDER BY m2.host")
        assert r.rows == [["b"]]

    def test_anti_join_is_null_not_pushed(self, jdb):
        # classic anti-join: IS NULL is satisfied by the NULL-filled miss
        # row, so it must NOT pre-filter the right side.  Float columns
        # NULL-fill as NaN (string misses stage as '' by the engine's
        # device-NULL convention, so the float column is the detector).
        r = jdb.sql("SELECT m2.host FROM m2 LEFT JOIN meta2 "
                    "ON m2.host = meta2.host WHERE meta2.w IS NULL "
                    "ORDER BY m2.host")
        assert [row[0] for row in r.rows] == ["c"]

    def test_full_join_predicates_not_pushed_unless_rejecting(self, jdb):
        r = jdb.sql("SELECT m2.host, meta2.dc FROM m2 FULL JOIN meta2 "
                    "ON m2.host = meta2.host WHERE meta2.w = 1 "
                    "ORDER BY m2.host")
        assert r.rows == [["a", "us"], ["a", "us"]]


class TestPushdownMissSemantics:
    """Review regressions: pushdown must preserve the engine's own
    miss-row semantics (sentinels, not SQL NULLs)."""

    @pytest.fixture
    def jdb(self, db):
        db.sql("CREATE TABLE m3 (host STRING, ts TIMESTAMP(3) TIME INDEX,"
               " cpu DOUBLE, PRIMARY KEY (host))")
        db.sql("CREATE TABLE meta3 (host STRING, ts TIMESTAMP(3) "
               "TIME INDEX, dc STRING, w DOUBLE, PRIMARY KEY (host))")
        db.sql("INSERT INTO m3 VALUES ('a',1000,10.0),('b',1000,30.0),"
               "('c',1000,40.0)")
        db.sql("INSERT INTO meta3 VALUES ('a',0,'us',1.0),"
               "('b',0,'eu',2.0)")
        return db

    def test_neq_on_right_side_not_pushed(self, jdb):
        # NaN != 1 is True under IEEE: a matched-and-dropped row must not
        # reappear as a NULL-filled miss via pushdown.  Engine semantics:
        # 'a' (w=1) dropped; 'b' (w=2) kept; 'c' (miss, NaN) kept.
        r = jdb.sql("SELECT m3.host FROM m3 LEFT JOIN meta3 "
                    "ON m3.host = meta3.host WHERE meta3.w != 1 "
                    "ORDER BY m3.host")
        assert [row[0] for row in r.rows] == ["b", "c"]

    def test_string_neq_not_pushed(self, jdb):
        # '' != 'us' is True: same trap through the string sentinel
        r = jdb.sql("SELECT m3.host FROM m3 LEFT JOIN meta3 "
                    "ON m3.host = meta3.host WHERE meta3.dc != 'us' "
                    "ORDER BY m3.host")
        assert [row[0] for row in r.rows] == ["b", "c"]

    def test_tag_literal_on_left_like_refused(self, jdb):
        from greptimedb_tpu.errors import GreptimeError

        # 'prod%' LIKE host would swap subject and pattern — refuse
        # loudly rather than silently matching host LIKE 'prod%'
        with pytest.raises(GreptimeError):
            jdb.sql("SELECT host FROM m3 WHERE 'prod%' LIKE host")


class TestSystemTableFullSurface:
    """System tables beyond the host mini-engine stage into the real
    engine: GROUP BY, non-count aggregates, expressions of aggregates."""

    def test_group_by_and_aggs(self, db):
        db.sql("CREATE TABLE s1 (h STRING, ts TIMESTAMP(3) TIME INDEX, "
               "v DOUBLE, PRIMARY KEY (h))")
        r = db.sql("SELECT table_schema, count(*) FROM "
                   "information_schema.tables GROUP BY table_schema "
                   "ORDER BY table_schema")
        schemas = [row[0] for row in r.rows]
        assert "public" in schemas and "information_schema" in schemas
        assert db.sql("SELECT count(*) > 0 FROM "
                      "information_schema.engines").rows == [[True]]
        assert db.sql("SELECT max(ordinal_position) FROM "
                      "information_schema.columns").rows == [[3]]
