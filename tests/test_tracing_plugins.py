"""OTLP span export, script (vrl-analog) pipeline processor, plugins.

Reference: common-telemetry OTLP tracing export, etl vrl_processor.rs,
the plugins crate.
"""

import sys
import textwrap
import types

import pytest

from greptimedb_tpu.errors import InvalidArguments, Unsupported
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.utils.tracing import TRACER, Tracer, encode_spans


class TestTracer:
    def test_disabled_records_nothing(self):
        t = Tracer()
        with t.span("x"):
            pass
        assert t.drain() == []

    def test_span_recording_and_parenting(self):
        t = Tracer()
        t.configure(enabled=True)
        with t.span("outer", q="SELECT 1"):
            with t.span("inner"):
                pass
        spans = t.drain()
        assert [s["name"] for s in spans] == ["inner", "outer"]
        inner, outer = spans
        assert inner.get("trace_id") == outer["trace_id"]
        assert inner["parent_span_id"] == outer["span_id"]
        assert outer["parent_span_id"] == ""
        assert outer["attributes"] == {"q": "SELECT 1"}
        assert outer["end_ns"] >= outer["start_ns"]

    def test_error_sets_status(self):
        t = Tracer()
        t.configure(enabled=True)
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("x")
        assert t.drain()[0]["status_code"] == 2

    def test_buffer_bounded(self):
        t = Tracer()
        t.configure(enabled=True)
        t.max_buffer = 10
        for i in range(25):
            with t.span(f"s{i}"):
                pass
        assert len(t.drain()) == 10

    def test_encode_parses_back(self):
        # round-trip through the server-side OTLP parser
        from greptimedb_tpu.servers.trace import parse_otlp_traces

        t = Tracer()
        t.configure(enabled=True)
        with t.span("hello", table="cpu"):
            pass
        body = encode_spans("svc-a", t.drain())
        cols = parse_otlp_traces(body)
        assert cols["service_name"] == ["svc-a"]
        assert cols["span_name"] == ["hello"]
        assert '"table": "cpu"' in cols["attributes"][0]

    def test_export_to_another_instance(self):
        # dogfood: instance A's spans land in instance B's trace table
        from greptimedb_tpu.servers.http import HttpServer

        sink = GreptimeDB()
        srv = HttpServer(sink, port=0)
        srv.start()
        try:
            src = GreptimeDB()
            TRACER.configure(
                endpoint=f"http://127.0.0.1:{srv.port}/v1/otlp/v1/traces",
                service_name="greptime-src")
            src.sql("CREATE TABLE t (h STRING, ts TIMESTAMP(3) TIME INDEX,"
                    " PRIMARY KEY (h))")
            src.sql("SELECT 1")
            n = TRACER.flush()
            assert n >= 4  # sql + execute_statement spans per query
            rows = sink.sql(
                "SELECT service_name, span_name FROM opentelemetry_traces"
                " WHERE service_name = 'greptime-src'").rows
            assert rows and {r[1] for r in rows} >= {
                "sql", "execute_statement"}
            src.close()
        finally:
            TRACER.disable()
            srv.stop()
            sink.close()


class TestScriptProcessor:
    def run(self, source, row):
        from greptimedb_tpu.servers.pipeline import ScriptProcessor

        return ScriptProcessor(source).apply(dict(row))

    def test_assignment_and_arithmetic(self):
        out = self.run(".ms = .s * 1000\n.total = .a + .b",
                       {"s": 1.5, "a": 2, "b": 3})
        assert out["ms"] == 1500.0 and out["total"] == 5

    def test_string_functions_and_concat(self):
        out = self.run(
            '.lvl = upper(.level); .msg = .host + ": " + .text',
            {"level": "warn", "host": "h1", "text": "disk"})
        assert out["lvl"] == "WARN" and out["msg"] == "h1: disk"

    def test_if_and_comparisons(self):
        src = '.sev = if(.code >= 500, "error", "ok")'
        assert self.run(src, {"code": 503})["sev"] == "error"
        assert self.run(src, {"code": 200})["sev"] == "ok"

    def test_del_and_null_propagation(self):
        out = self.run("del(.secret)\n.x = .missing * 2",
                       {"secret": "s", "keep": 1})
        assert "secret" not in out and out["x"] is None and out["keep"] == 1

    def test_nested_and_bool_logic(self):
        out = self.run(
            ".flag = contains(.msg, \"err\") && .n > 1 || false",
            {"msg": "errors", "n": 5})
        assert out["flag"] is True

    def test_semicolon_inside_string_literal(self):
        out = self.run('.msg = replace(.msg, ";", ",")', {"msg": "a;b"})
        assert out["msg"] == "a,b"

    def test_if_is_lazy(self):
        src = ".rate = if(.total != 0, .hits / .total, 0)"
        assert self.run(src, {"hits": 4, "total": 2})["rate"] == 2.0
        assert self.run(src, {"hits": 4, "total": 0})["rate"] == 0

    def test_truncated_expression_is_clean_error(self):
        with pytest.raises(Unsupported, match="end of expression"):
            self.run(".x = 1 +", {})

    def test_rejects_arbitrary_code(self):
        with pytest.raises(Unsupported):
            self.run(".x = __import__('os')", {})
        with pytest.raises(Unsupported):
            self.run("import os", {})

    def test_pipeline_integration(self):
        db = GreptimeDB()
        import json as _json
        import urllib.request

        from greptimedb_tpu.servers.http import HttpServer

        srv = HttpServer(db, port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            pipeline = textwrap.dedent("""
                processors:
                  - vrl:
                      source: |
                        .level = upper(.level)
                        .latency_ms = .latency_s * 1000
                        del(.latency_s)
                transform:
                  - fields: [level]
                    type: string
                    index: tag
                  - fields: [latency_ms]
                    type: float64
                  - fields: [ts]
                    type: time
                    index: timestamp
            """)
            req = urllib.request.Request(
                base + "/v1/pipelines/vrltest", data=pipeline.encode(),
                method="POST", headers={"Content-Type": "application/x-yaml"})
            urllib.request.urlopen(req, timeout=10).read()
            doc = _json.dumps([{"level": "warn", "latency_s": 0.25,
                                "ts": 1700000000000}])
            req = urllib.request.Request(
                base + "/v1/ingest?db=public&table=vrl_logs"
                       "&pipeline_name=vrltest",
                data=doc.encode(), method="POST",
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10).read()
            rows = db.sql("SELECT level, latency_ms FROM vrl_logs").rows
            assert rows == [["WARN", 250.0]]
        finally:
            srv.stop()
            db.close()


class TestPlugins:
    def _mk_module(self, name, body):
        mod = types.ModuleType(name)
        exec(body, mod.__dict__)
        sys.modules[name] = mod
        return mod

    def test_scalar_function_plugin(self):
        self._mk_module("fake_udf_plugin", textwrap.dedent("""
            import numpy as np
            def double_it(args, n):
                return np.asarray(args[0], dtype=float) * 2
            def register(api):
                api.register_scalar_function("double_it", double_it)
        """))
        db = GreptimeDB(plugins=["fake_udf_plugin"])
        assert db.plugins.loaded == ["fake_udf_plugin"]
        db.sql("CREATE TABLE p (h STRING, ts TIMESTAMP(3) TIME INDEX,"
               " v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO p VALUES ('a', 1000, 2.5)")
        assert db.sql("SELECT double_it(v) FROM p").rows == [[5.0]]
        db.close()

    def test_processor_plugin(self):
        self._mk_module("fake_proc_plugin", textwrap.dedent("""
            class Redact:
                def __init__(self, cfg):
                    self.field = cfg.get("field", "msg")
                def apply(self, row):
                    if self.field in row:
                        row[self.field] = "[redacted]"
                    return row
            def register(api):
                api.register_processor("redact", lambda c: Redact(c or {}))
        """))
        from greptimedb_tpu.servers.pipeline import _PROCESSORS

        db = GreptimeDB(plugins=["fake_proc_plugin"])
        assert "redact" in _PROCESSORS
        proc = _PROCESSORS["redact"]({"field": "msg"})
        assert proc.apply({"msg": "secret"})["msg"] == "[redacted]"
        db.close()
        del _PROCESSORS["redact"]

    def test_missing_plugin_fails_fast(self):
        with pytest.raises(InvalidArguments, match="no_such_plugin"):
            GreptimeDB(plugins=["no_such_plugin_xyz"]).close()

    def test_plugin_without_register_rejected(self):
        self._mk_module("fake_empty_plugin", "x = 1")
        with pytest.raises(InvalidArguments, match="register"):
            GreptimeDB(plugins=["fake_empty_plugin"]).close()
