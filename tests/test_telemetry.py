"""End-to-end query telemetry: registry exposition, span-instrumented
engines, device-phase metrics, cache/HBM gauges, zero-overhead disabled
tracing.

Reference counterparts: per-crate metric registries exported at /metrics
(src/servers/src/http.rs:944), common-telemetry span instrumentation
(src/common/telemetry), slow-query recorder (common-event-recorder).
"""

import json
import re

import pytest

from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.utils.telemetry import (
    REGISTRY, Counter, Gauge, Histogram, Registry,
)
from greptimedb_tpu.utils.tracing import TRACER, render_span_tree


@pytest.fixture
def db():
    d = GreptimeDB()
    d.sql("CREATE TABLE cpu (h STRING, ts TIMESTAMP(3) TIME INDEX, "
          "v DOUBLE, PRIMARY KEY (h))")
    d.sql("INSERT INTO cpu VALUES ('a', 1000, 1.0), ('b', 2000, 2.0), "
          "('a', 3000, 3.0), ('b', 4000, 4.0)")
    yield d
    d.close()


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

class TestExposition:
    def test_label_value_escaping(self):
        r = Registry()
        c = r.counter("esc_total", "escapes", labels=("q",))
        c.labels('he said "hi"\\path\nnext').inc()
        text = r.render()
        assert 'q="he said \\"hi\\"\\\\path\\nnext"' in text
        assert "\n q=" not in text  # the newline never splits the line

    def test_help_escaping(self):
        r = Registry()
        r.counter("h_total", "line1\nline2 \\ backslash").inc()
        line = next(l for l in r.render().splitlines()
                    if l.startswith("# HELP h_total"))
        assert line == "# HELP h_total line1\\nline2 \\\\ backslash"

    def test_type_lines(self):
        r = Registry()
        r.counter("a_total").inc()
        r.gauge("b_bytes").set(2)
        r.histogram("c_seconds").observe(0.1)
        text = r.render()
        assert "# TYPE a_total counter" in text
        assert "# TYPE b_bytes gauge" in text
        assert "# TYPE c_seconds histogram" in text
        assert "a_total 1.0" in text
        assert "b_bytes 2" in text

    def test_histogram_cumulative_buckets_end_in_inf(self):
        r = Registry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = r.render().splitlines()
        buckets = [l for l in lines if l.startswith("lat_seconds_bucket")]
        # cumulative counts, +Inf last and equal to the observation count
        assert buckets == [
            'lat_seconds_bucket{le="0.1"} 1',
            'lat_seconds_bucket{le="1.0"} 3',
            'lat_seconds_bucket{le="10.0"} 4',
            'lat_seconds_bucket{le="+Inf"} 5',
        ]
        assert "lat_seconds_count 5" in lines
        assert any(l.startswith("lat_seconds_sum") for l in lines)

    def test_gauge_set_function_pull(self):
        r = Registry()
        g = r.gauge("pull_bytes")
        state = {"v": 7.0}
        g.set_function(lambda: state["v"])
        assert "pull_bytes 7.0" in r.render()
        state["v"] = 9.0
        assert "pull_bytes 9.0" in r.render()

    def test_export_samples_histogram_explosion(self):
        # the self-import sample shape: cumulative _bucket rows with an
        # le label ending in +Inf, plus _sum/_count — same layout the
        # OTLP ingest path produces, so histogram_quantile just works
        r = Registry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        samples = {(n, tuple(sorted(lab.items()))): v
                   for n, lab, v in r.export_samples()}
        assert samples[("lat_seconds_bucket", (("le", "0.1"),))] == 1.0
        assert samples[("lat_seconds_bucket", (("le", "1.0"),))] == 1.0
        assert samples[("lat_seconds_bucket", (("le", "+Inf"),))] == 2.0
        assert samples[("lat_seconds_count", ())] == 2.0
        assert samples[("lat_seconds_sum", ())] == pytest.approx(5.05)

    def test_registry_value_reader(self):
        r = Registry()
        c = r.counter("v_total", labels=("k",))
        c.labels("x").inc(3)
        assert r.value("v_total", ("x",)) == 3.0
        assert r.value("v_total", ("missing",)) == 0.0
        assert r.value("absent_total") == 0.0


# ---------------------------------------------------------------------------
# Tier-1 registry static check (duplicate registrations + name convention)
# ---------------------------------------------------------------------------

class TestRegistryStaticCheck:
    def test_collision_detection(self):
        r = Registry()
        r.counter("dup_total")
        r.gauge("dup_total")  # kind mismatch
        r.counter("lbl_total", labels=("a",))
        r.counter("lbl_total", labels=("b",))  # label-set mismatch
        assert len(r.collisions) == 2

    def test_process_registry_is_clean(self):
        # import every metric-registering module, then walk the REGISTRY:
        # no conflicting re-registrations, and every metric/label name
        # follows the Prometheus [a-z_][a-z0-9_]* convention
        import greptimedb_tpu.compile.service  # noqa: F401
        import greptimedb_tpu.flow.engine  # noqa: F401
        import greptimedb_tpu.meta.cluster  # noqa: F401
        import greptimedb_tpu.meta.migration  # noqa: F401
        import greptimedb_tpu.parallel.dist  # noqa: F401
        import greptimedb_tpu.promql.engine  # noqa: F401
        import greptimedb_tpu.query.physical  # noqa: F401
        import greptimedb_tpu.rpc.frontend  # noqa: F401
        import greptimedb_tpu.servers.http  # noqa: F401
        import greptimedb_tpu.servers.protocols  # noqa: F401
        import greptimedb_tpu.servers.tcp  # noqa: F401
        import greptimedb_tpu.serving.scheduler  # noqa: F401
        import greptimedb_tpu.standalone  # noqa: F401
        import greptimedb_tpu.storage.cache  # noqa: F401
        import greptimedb_tpu.storage.wal  # noqa: F401
        import greptimedb_tpu.utils.chaos  # noqa: F401
        import greptimedb_tpu.utils.memory  # noqa: F401

        # the convention/collision logic lives in the analyzer's hygiene
        # pass now (single source of truth): check_registry is the
        # RUNTIME twin of the static GL-T001/T002/T003 checks, applied
        # to whatever actually registered (dynamic names included)
        from greptimedb_tpu.analysis.passes.hygiene import check_registry

        assert check_registry(REGISTRY) == []
        for m in REGISTRY._metrics.values():
            assert isinstance(m, (Counter, Gauge, Histogram))
        # the serving scheduler's first-class metric surface must exist
        # by import (not lazily on first query): /metrics scrapes on an
        # idle instance still show the queue/batch/admission families
        for required in (
            "greptime_scheduler_queue_depth",
            "greptime_scheduler_wait_seconds",
            "greptime_scheduler_batch_size",
            "greptime_scheduler_batches_total",
            "greptime_scheduler_batched_queries_total",
            "greptime_scheduler_shed_total",
            "greptime_scheduler_executed_total",
            "greptime_scheduler_admitted_total",
            "greptime_scheduler_rejected_total",
            "greptime_scheduler_tenant_inflight",
        ):
            assert required in REGISTRY._metrics, required
        # the query-compiler subsystem's surface (persistent compile
        # cache hits/misses/persists, AOT warmup outcomes, fused
        # dispatches) exists by import for the same reason
        for required in (
            "greptime_compile_cache_events_total",
            "greptime_compile_xla_builds_total",
            "greptime_compile_fused_dispatch_total",
            "greptime_compile_warmup_total",
            "greptime_compile_cache_disk_bytes",
        ):
            assert required in REGISTRY._metrics, required
        # the vectorized ingest pipeline's metric surface likewise exists
        # by import: wire decode (rows/bytes/batches/parse-phase seconds,
        # the object-decode pin the hot path holds at 0) and the WAL
        # group-commit batch/fsync accounting
        for required in (
            "greptime_ingest_rows_total",
            "greptime_ingest_bytes_total",
            "greptime_ingest_batches_total",
            "greptime_ingest_parse_seconds",
            "greptime_ingest_object_decode_rows_total",
            "greptime_ingest_wal_batch_size",
            "greptime_ingest_wal_fsyncs_total",
        ):
            assert required in REGISTRY._metrics, required
        # the durability surface (corruption triage, quarantine, repair)
        # likewise exists by import: an idle /metrics scrape must already
        # expose the counters operators alert on
        import greptimedb_tpu.storage.durability  # noqa: F401

        for required in (
            "greptime_durability_corruption_total",
            "greptime_durability_quarantined_total",
            "greptime_durability_repaired_total",
        ):
            assert required in REGISTRY._metrics, required
        # the fulltext fingerprint index: candidates/verified/matched
        # (false-positive ratio), selectivity, per-path query counts and
        # resident bytes — the surface bench_logs.py reads
        import greptimedb_tpu.fulltext.resident  # noqa: F401

        for required in (
            "greptime_fulltext_candidates_total",
            "greptime_fulltext_verified_total",
            "greptime_fulltext_matched_total",
            "greptime_fulltext_scanned_total",
            "greptime_fulltext_queries_total",
            "greptime_fulltext_indexed_values_total",
            "greptime_fulltext_resident_bytes",
        ):
            assert required in REGISTRY._metrics, required
        # the SLO observatory + idle economy (serving/slo.py, serving/
        # idle.py): sketches, error budgets, burn rates, and the
        # idle-grant ledger — the surface the self-monitor loop and
        # bench_soak.py gate on
        import greptimedb_tpu.serving.idle  # noqa: F401
        import greptimedb_tpu.serving.slo  # noqa: F401

        for required in (
            "greptime_slo_latency",
            "greptime_slo_budget_remaining",
            "greptime_slo_burn_rate",
            "greptime_idle_granted_total",
            "greptime_idle_elapsed_seconds_total",
            "greptime_idle_starved_total",
            "greptime_idle_throttled_total",
        ):
            assert required in REGISTRY._metrics, required

    def test_self_export_table_naming(self):
        # the self-import loop (utils/selfmonitor.py) names tables after
        # registry metrics: every name must round-trip through the OTLP
        # normalizer unchanged, and the prometheus-style histogram
        # explosion (_bucket/_sum/_count) must not collide with any
        # other registered metric's table
        import greptimedb_tpu.flow.engine  # noqa: F401
        import greptimedb_tpu.parallel.dist  # noqa: F401
        import greptimedb_tpu.promql.engine  # noqa: F401
        import greptimedb_tpu.query.physical  # noqa: F401
        import greptimedb_tpu.servers.http  # noqa: F401
        import greptimedb_tpu.servers.tcp  # noqa: F401
        import greptimedb_tpu.serving.scheduler  # noqa: F401
        import greptimedb_tpu.standalone  # noqa: F401
        import greptimedb_tpu.storage.cache  # noqa: F401
        import greptimedb_tpu.utils.memory  # noqa: F401
        from greptimedb_tpu.analysis.passes.hygiene import check_registry
        from greptimedb_tpu.servers.otlp import _norm

        # delegated to the hygiene pass's runtime twin: histogram
        # explosion collisions + the OTLP normalizer round-trip
        assert check_registry(REGISTRY, norm=_norm) == []


# ---------------------------------------------------------------------------
# Instance identity + workload gauges
# ---------------------------------------------------------------------------

class TestInstanceMetrics:
    def test_build_info_and_uptime(self):
        from greptimedb_tpu import __version__

        text = REGISTRY.render()
        assert f'greptime_build_info{{version="{__version__}"' in text
        m = re.search(r"(?m)^greptime_process_uptime_seconds (\S+)$", text)
        assert m and float(m.group(1)) >= 0.0
        assert "greptime_process_start_time_seconds" in text

    def test_workload_hbm_gauges(self, db):
        text = REGISTRY.render()
        for wl in ("ingest", "device_cache", "layout_cache", "promql_cache"):
            assert f'greptime_memory_workload_used_bytes{{workload="{wl}"}}' \
                in text
        # pull-mode: the gauge reads the same number usage() reports
        used = db.memory.usage()["device_cache"]["used_bytes"]
        assert REGISTRY.value("greptime_memory_workload_used_bytes",
                              ("device_cache",)) == float(used)

    def test_runtime_metrics_carries_identity(self, db):
        r = db.sql("SELECT metric_name FROM information_schema.runtime_metrics"
                   " WHERE metric_name LIKE 'greptime_build%'")
        assert ["greptime_build_info"] in r.rows


# ---------------------------------------------------------------------------
# Query latency histograms + cache counters in the registry
# ---------------------------------------------------------------------------

class TestQueryTelemetry:
    def test_engine_histograms(self, db):
        sql0 = REGISTRY.value("greptime_query_duration_seconds", ("sql",))
        tql0 = REGISTRY.value("greptime_query_duration_seconds", ("promql",))
        db.sql("SELECT h, avg(v) FROM cpu GROUP BY h")
        db.sql("TQL EVAL (0, 10, '5s') avg(cpu)")
        assert REGISTRY.value(
            "greptime_query_duration_seconds", ("sql",)) > sql0
        assert REGISTRY.value(
            "greptime_query_duration_seconds", ("promql",)) > tql0

    def test_device_phase_split(self, db):
        # a never-seen GROUP BY shape forces a jit-cache miss → the
        # compile phase is observed; EXPLAIN ANALYZE then shows the
        # steady-state device wait next to the jit_cache annotation
        c0 = REGISTRY.value("greptime_device_phase_seconds",
                            ("sql", "compile"))
        db.sql("SELECT h, min(v), max(v), count(v) FROM cpu GROUP BY h")
        assert REGISTRY.value("greptime_device_phase_seconds",
                              ("sql", "compile")) > c0
        r = db.sql("EXPLAIN ANALYZE SELECT h, min(v), max(v), count(v) "
                   "FROM cpu GROUP BY h")
        analyze = r.rows[1][1]
        assert "jit_cache:" in analyze
        assert "device_wait_ms:" in analyze

    def test_promql_stage_histogram(self, db):
        s0 = REGISTRY.value("greptime_promql_stage_seconds", ("selection",))
        db.sql("TQL EVAL (0, 10, '5s') sum by(h) (cpu)")
        assert REGISTRY.value(
            "greptime_promql_stage_seconds", ("selection",)) > s0

    def test_promql_cache_counters_mirror_registry(self, db):
        ev = "greptime_cache_events_total"
        h0 = REGISTRY.value(ev, ("promql", "selection", "hit"))
        db.sql("TQL EVAL (0, 10, '5s') avg(cpu)")
        db.sql("TQL EVAL (0, 10, '5s') avg(cpu)")  # warm: selection hit
        assert REGISTRY.value(ev, ("promql", "selection", "hit")) > h0
        # instance counters and registry mirror move together
        assert db.promql_cache.hits["selection"] > 0

    def test_region_cache_counters(self, db):
        ev = "greptime_cache_events_total"
        before = REGISTRY.value(ev, ("region_device", "table", "hit"))
        db.sql("SELECT * FROM cpu ORDER BY ts LIMIT 1")
        db.sql("SELECT * FROM cpu ORDER BY ts LIMIT 1")
        assert REGISTRY.value(ev, ("region_device", "table", "hit")) > before

    def test_flow_tick_metrics(self, db):
        db.sql("CREATE FLOW f_cnt SINK TO cpu_hourly AS "
               "SELECT h, count(v) AS c, date_trunc('hour', ts) AS hr "
               "FROM cpu GROUP BY h, hr")
        r0 = REGISTRY.value("greptime_flow_rows_total", ("f_cnt",))
        db.sql("INSERT INTO cpu VALUES ('c', 5000, 5.0)")
        assert REGISTRY.value("greptime_flow_rows_total", ("f_cnt",)) >= r0
        assert REGISTRY.value("greptime_flow_tick_duration_seconds",
                              ("f_cnt", "streaming")) > 0


# ---------------------------------------------------------------------------
# Zero-overhead disabled tracing (pins the seed fast path)
# ---------------------------------------------------------------------------

class TestDisabledTracingZeroOverhead:
    def test_no_span_objects_allocated(self, db):
        assert not TRACER.enabled

        def boom(*a, **k):  # any span() call while disabled is a bug
            raise AssertionError("span allocated with tracer disabled")

        TRACER.span = boom
        try:
            db.sql("SELECT h, avg(v) FROM cpu GROUP BY h")
            db.sql("TQL EVAL (0, 10, '5s') sum by(h) (cpu)")
        finally:
            del TRACER.__dict__["span"]
        assert TRACER._spans == []

    def test_explain_analyze_seed_format_unchanged(self, db):
        r = db.sql("EXPLAIN ANALYZE SELECT h, avg(v) FROM cpu GROUP BY h")
        assert r.column_names == ["plan_type", "plan"]
        # seed shape: exactly the logical plan + one analyze row, no
        # span-tree row, every analyze line `key: value (warm: value)`
        assert [row[0] for row in r.rows] == [
            "logical_plan (tpu)", "analyze (cold vs warm ms)"]
        for line in r.rows[1][1].splitlines():
            assert re.match(r"^[a-z_]+: .+ \(warm: .+\)$", line), line


# ---------------------------------------------------------------------------
# Span-instrumented engines (tracer on)
# ---------------------------------------------------------------------------

class TestSpanTrees:
    @pytest.fixture
    def traced(self):
        TRACER.configure(enabled=True)
        TRACER.drain()
        yield TRACER
        TRACER.disable()

    def test_sql_stage_spans(self, db, traced):
        db.sql("SELECT h, avg(v) FROM cpu GROUP BY h")
        names = {s["name"] for s in traced.drain()}
        assert {"sql", "execute_statement", "parse", "optimize", "plan",
                "execute", "materialize"} <= names

    def test_promql_stage_spans(self, db, traced):
        db.sql("TQL EVAL (0, 10, '5s') sum by(h) (cpu)")
        names = {s["name"] for s in traced.drain()}
        # the fused chain (compile/fused.py) replaces the window-kernel +
        # eager-reduce pair with ONE fused_kernel span; PLAN_FUSION=off
        # (and every unfusable shape) keeps the window_kernel span
        assert {"selection", "sort_layout", "group_agg",
                "label_decode"} <= names
        assert "fused_kernel" in names or "window_kernel" in names

    def test_promql_stage_spans_unfused(self, db, traced, monkeypatch):
        monkeypatch.setenv("GREPTIME_PLAN_FUSION", "off")
        db.sql("TQL EVAL (0, 10, '5s') sum by(h) (cpu)")
        names = {s["name"] for s in traced.drain()}
        assert {"selection", "sort_layout", "window_kernel", "group_agg",
                "label_decode"} <= names

    def test_explain_analyze_span_tree_row(self, db, traced):
        r = db.sql("EXPLAIN ANALYZE SELECT h, avg(v) FROM cpu GROUP BY h")
        labels = [row[0] for row in r.rows]
        assert "analyze (span tree, warm run)" in labels
        tree = r.rows[labels.index("analyze (span tree, warm run)")][1]
        assert "execute" in tree and "materialize" in tree
        assert re.search(r"execute: \d+\.\d+ ms", tree)

    def test_mark_since_windowing(self, traced):
        with traced.span("a"):
            pass
        m = traced.mark()
        with traced.span("b"):
            pass
        assert [s["name"] for s in traced.since(m)] == ["b"]
        # drain moves the window; since() never resurrects drained spans
        traced.drain()
        assert traced.since(m) == []

    def test_render_span_tree_nesting(self, traced):
        with traced.span("outer"):
            with traced.span("inner"):
                pass
        tree = render_span_tree(traced.drain())
        lines = tree.splitlines()
        assert lines[0].startswith("outer:")
        assert lines[1].startswith("  inner:")


# ---------------------------------------------------------------------------
# Slow-query stage self-reporting
# ---------------------------------------------------------------------------

class TestSlowQueryStages:
    def test_sql_and_tql_stage_breakdown(self, db):
        db.sql("TQL EVAL (0, 10, '5s') avg(cpu)")  # warm the kernel class
        db.slow_query_threshold_ms = 0.0001
        try:
            db.sql("SELECT h, avg(v) FROM cpu GROUP BY h")
            db.sql("TQL EVAL (0, 10, '5s') avg(cpu)")
        finally:
            db.slow_query_threshold_ms = 0.0
        r = db.sql("SELECT query, stages FROM greptime_private.slow_queries")
        by_query = {q: s for q, s in r.rows}
        sql_stages = json.loads(
            by_query["SELECT h, avg(v) FROM cpu GROUP BY h"])
        assert "plan_ms" in sql_stages and "device_exec_ms" in sql_stages
        tql_stages = json.loads(by_query["TQL EVAL (0, 10, '5s') avg(cpu)"])
        # fused chain reports its one dispatch as fused_kernel; unfused
        # (PLAN_FUSION=off, unfusable shapes) keeps window_kernel
        assert ("promql_fused_kernel_ms" in tql_stages
                or "promql_window_kernel_ms" in tql_stages)
        assert "promql_selection_ms" in tql_stages
