"""Chaos tier: random SIGKILL against live OS processes, then recovery
invariants (reference tests-fuzz/targets/failover/ + unstable/ — pod
kills under kind; here: process kills under pytest).

Invariants checked after every kill:
  1. no data loss post-WAL-ack: every insert the client saw acknowledged
     is present after reopen (SIGKILL preserves completed write()s);
  2. manifest consistency: every region opens cleanly and scans;
  3. control-plane resume: journaled DDL procedures finish on restart
     and the instance accepts new DDL/DML.

Deterministic by default (seeded); scale with GREPTIME_CHAOS_ROUNDS.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.fuzz

ROUNDS = int(os.environ.get("GREPTIME_CHAOS_ROUNDS", "3"))
SEED = int(os.environ.get("GREPTIME_FUZZ_SEED", "11"))

_INGEST_CHILD = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.storage.region import RegionOptions

home, ack_path = sys.argv[1], sys.argv[2]
db = GreptimeDB(home, region_options=RegionOptions(wal_enabled=True))
db.sql("CREATE TABLE IF NOT EXISTS c (h STRING, ts TIMESTAMP(3) TIME INDEX,"
       " v DOUBLE, PRIMARY KEY (h))")
ack = open(ack_path, "a")
start = int(open(ack_path).read().splitlines()[-1]) + 1 if (
    os.path.getsize(ack_path) > 0) else 0
print("ready", flush=True)
batch = start
while True:
    t0 = 1700000000000 + batch * 10_000
    db.sql("INSERT INTO c VALUES " + ",".join(
        f"('h{i % 5}',{t0 + i},{batch}.0)" for i in range(10)))
    # the WAL append returned: this batch is acked
    ack.write(f"{batch}\n")
    ack.flush()
    os.fsync(ack.fileno())
    batch += 1
"""

_DDL_CHILD = r"""
import random, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.errors import GreptimeError

home, seed = sys.argv[1], int(sys.argv[2])
rng = random.Random(seed)
db = GreptimeDB(home)
print("ready", flush=True)
n = 0
while True:
    name = f"t{rng.randrange(6)}"
    op = rng.random()
    try:
        if op < 0.35:
            db.sql(f"CREATE TABLE IF NOT EXISTS {name} (h STRING, "
                   "ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        elif op < 0.55:
            db.sql(f"DROP TABLE IF EXISTS {name}")
        elif op < 0.7:
            db.sql(f"ALTER TABLE {name} ADD COLUMN c{rng.randrange(4)} "
                   "DOUBLE")
        elif op < 0.85:
            db.sql(f"INSERT INTO {name} VALUES "
                   f"('a', {1700000000000 + n}, 1.0)")
        else:
            db.sql(f"ALTER TABLE {name} SET ttl='{rng.randrange(1, 9)}d'")
    except GreptimeError:
        pass  # typed rejections are legal; crashes are not
    n += 1
"""


def _spawn(code: str, *args) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", code, *args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo",
    )


def _reopen_and_check(home: str):
    """Reopen the data home in-process and verify storage invariants."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from greptimedb_tpu.standalone import GreptimeDB

    db = GreptimeDB(home)
    try:
        for t in db.catalog.list_tables("public"):
            if t.engine not in ("mito",):
                continue
            for region in db._regions_of(t.name):  # lazy open-or-create
                region.scan_host()  # manifest + SSTs + WAL replay coherent
        # the instance still takes DDL + DML after recovery
        db.sql("CREATE TABLE IF NOT EXISTS postcheck (h STRING, "
               "ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO postcheck VALUES ('x', 1, 1.0)")
        assert db.sql("SELECT count(*) FROM postcheck").num_rows == 1
        db.sql("DROP TABLE postcheck")
        return db
    except Exception:
        db.close()
        raise


class TestIngestKillRecovery:
    def test_no_acked_loss_across_kills(self, tmp_path):
        rng = random.Random(SEED)
        home = str(tmp_path / "chaos")
        ack_path = str(tmp_path / "acked.log")
        open(ack_path, "w").close()
        for rnd in range(ROUNDS):
            p = _spawn(_INGEST_CHILD, home, ack_path)
            assert p.stdout.readline().strip() == "ready"
            # wait for at least one acked batch (first INSERT may pay a
            # jax compile), then a random extra window
            deadline = time.time() + 60
            while os.path.getsize(ack_path) == 0:
                assert time.time() < deadline, "no ack within 60s"
                time.sleep(0.05)
            time.sleep(rng.uniform(0.2, 1.0))  # let more batches flow
            p.send_signal(signal.SIGKILL)
            p.wait()
            acked = [int(l) for l in open(ack_path).read().split()]
            db = _reopen_and_check(home)
            try:
                got = db.sql("SELECT count(*) FROM c").rows[0][0]
                assert got >= len(acked) * 10, (
                    f"round {rnd}: lost acked rows: {got} < "
                    f"{len(acked) * 10}")
                # acked batches are complete (no torn batch visible)
                r = db.sql("SELECT v, count(*) FROM c GROUP BY v")
                for v, cnt in r.rows:
                    if int(v) in set(acked):
                        assert cnt == 10, (v, cnt)
            finally:
                db.close()
            assert len(acked) > 0, "chaos round produced no acked batches"


class TestDdlKillRecovery:
    def test_ddl_procedures_resume(self, tmp_path):
        rng = random.Random(SEED + 1)
        home = str(tmp_path / "ddlchaos")
        for rnd in range(ROUNDS):
            p = _spawn(_DDL_CHILD, home, str(SEED + rnd))
            assert p.stdout.readline().strip() == "ready"
            time.sleep(rng.uniform(0.3, 1.0))
            p.send_signal(signal.SIGKILL)
            p.wait()
            db = _reopen_and_check(home)
            try:
                # procedure journal holds no stuck runners after resume
                from greptimedb_tpu.meta.procedure import (
                    ProcedureManager, ProcedureState,
                )

                pending = [
                    k for k, _v in db.kv.range(ProcedureManager._PREFIX)
                    if json.loads(_v).get("status")
                    == ProcedureState.RUNNING.value
                ]
                assert not pending, pending
            finally:
                db.close()


class TestFailoverChaos:
    def test_random_kill_then_migrate(self, tmp_path):
        """Writes flow to a remote-WAL datanode process; a random-time
        SIGKILL hits it; migration to the second process must expose
        every acked write (reference tests-fuzz/targets/failover/)."""
        from greptimedb_tpu.datatypes import (
            ColumnSchema, ConcreteDataType as T, Schema, SemanticType as S,
        )
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.frontend import RemoteDatanode

        rng = random.Random(SEED + 2)
        storage = str(tmp_path / "store")
        wal = str(tmp_path / "broker")
        procs, addrs = [], []
        for i in range(2):
            p = subprocess.Popen(
                [sys.executable, "-m", "greptimedb_tpu.cli", "datanode",
                 "start", "--node-id", str(i), "--data-home", storage,
                 "--remote-wal-dir", wal, "--managed", "--platform", "cpu"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd="/root/repo")
            procs.append(p)
            addrs.append(json.loads(p.stdout.readline())["address"])
        try:
            sch = Schema((
                ColumnSchema("h", T.STRING, S.TAG),
                ColumnSchema("ts", T.TIMESTAMP_MILLISECOND, S.TIMESTAMP),
                ColumnSchema("v", T.FLOAT64, S.FIELD),
            ))
            ms = Metasrv(MemoryKv())
            proxies = [RemoteDatanode(i, a) for i, a in enumerate(addrs)]
            for pr in proxies:
                ms.register_datanode(pr)
            rid = 777
            proxies[0].handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "leader",
                 "schema": sch.to_dict()}, 0.0)
            ms.set_region_route(rid, 0)
            acked = 0
            kill_after = rng.randrange(3, 12)
            for k in range(40):
                try:
                    proxies[0].write(
                        rid, {"h": [f"h{k % 3}"], "ts": [1000 + k],
                              "v": [float(k)]}, float(k))
                    acked += 1
                except Exception:  # noqa: BLE001 — killed mid-write
                    break
                if rng.random() < 0.2 and k % 5 == 0:
                    proxies[0].client.instruction(
                        {"kind": "flush_region", "region_id": rid})
                if k == kill_after:
                    procs[0].send_signal(signal.SIGKILL)
                    procs[0].wait()
                    break
            ms.migrate_region(rid, 0, 1, now_ms=100.0)
            host = proxies[1].read(rid)
            assert len(host["ts"]) >= acked, (len(host["ts"]), acked)
            # the survivor keeps serving writes
            proxies[1].write(rid, {"h": ["z"], "ts": [9999], "v": [9.0]},
                             200.0)
            assert len(proxies[1].read(rid)["ts"]) >= acked + 1
            DatanodeClient(addrs[1]).action("shutdown")
            procs[1].wait(timeout=20)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=10)


_REPARTITION_CHILD = r"""
import os, random, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.meta.repartition import repartition_table
from greptimedb_tpu.errors import GreptimeError

home, ack_path, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
rng = random.Random(seed)
db = GreptimeDB(home)
db.sql("CREATE TABLE IF NOT EXISTS rp (h STRING, ts TIMESTAMP(3) "
       "TIME INDEX, v DOUBLE, PRIMARY KEY (h)) "
       "PARTITION ON COLUMNS (h) (h < 'm', h >= 'm')")
ack = open(ack_path, "a")
start = int(open(ack_path).read().splitlines()[-1]) + 1 if (
    os.path.getsize(ack_path) > 0) else 0
print("ready", flush=True)
batch = start
rules = [
    (["h"], ["h < 'm'", "h >= 'm'"]),
    (["h"], ["h < 'g'", "h >= 'g' AND h < 't'", "h >= 't'"]),
    ([], []),  # merge to one region
]
while True:
    t0 = 1700000000000 + batch * 100
    db.sql("INSERT INTO rp VALUES " + ",".join(
        f"('{c}{batch % 7}',{t0 + i},{batch}.0)"
        for i, c in enumerate("aghz")))
    ack.write(f"{batch}\n"); ack.flush(); os.fsync(ack.fileno())
    if batch % 3 == 2:
        cols, exprs = rules[rng.randrange(len(rules))]
        try:
            repartition_table(db, "rp", cols, exprs)
        except GreptimeError:
            pass  # same-rule rejection etc.
    batch += 1
"""


class TestRepartitionChaos:
    def test_kill_mid_repartition(self, tmp_path):
        """SIGKILL lands while repartitions (journaled procedures that
        create/retire regions and rewrite routes) interleave with acked
        writes; after reopen the journal must have converged (startup
        resume) and every acked batch must be intact
        (reference tests-fuzz/targets/ddl/fuzz_repartition_table_chaos.rs)."""
        rng = random.Random(SEED + 3)
        home = str(tmp_path / "rpchaos")
        ack_path = str(tmp_path / "acked.log")
        open(ack_path, "w").close()
        for rnd in range(ROUNDS):
            p = _spawn(_REPARTITION_CHILD, home, ack_path, str(SEED + rnd))
            assert p.stdout.readline().strip() == "ready"
            deadline = time.time() + 90
            want = 3 * (rnd + 1)  # let several repartitions happen
            while sum(1 for _ in open(ack_path)) < want:
                assert time.time() < deadline, "no progress within 90s"
                time.sleep(0.05)
            time.sleep(rng.uniform(0.05, 0.6))  # land mid-procedure
            p.send_signal(signal.SIGKILL)
            p.wait()
            acked = [int(l) for l in open(ack_path).read().split()]
            db = _reopen_and_check(home)
            try:
                # journal converged: startup resume left nothing RUNNING
                from greptimedb_tpu.meta.procedure import (
                    ProcedureManager, ProcedureState,
                )

                stuck = [
                    k for k, v in db.kv.range(ProcedureManager._PREFIX)
                    if json.loads(v).get("status")
                    == ProcedureState.RUNNING.value
                ]
                assert not stuck, stuck
                # every acked batch fully present (4 rows each)
                r = db.sql("SELECT v, count(*) FROM rp GROUP BY v")
                got = {int(float(v)): c for v, c in r.rows}
                for b in acked:
                    assert got.get(b) == 4, (rnd, b, got.get(b))
                # the table still accepts writes and repartitions
                from greptimedb_tpu.meta.repartition import (
                    repartition_table,
                )

                db.sql("INSERT INTO rp VALUES ('q', 1, -1.0)")
                repartition_table(db, "rp", ["h"],
                                  ["h < 'x'", "h >= 'x'"])
                assert db.sql(
                    "SELECT count(*) FROM rp WHERE v = -1.0"
                ).rows[0][0] == 1
            finally:
                db.close()


class TestMigrationChaos:
    def test_kill_target_mid_migration(self, tmp_path):
        """The migration TARGET dies while the journaled state machine
        runs (open_candidate → … → close_old); the failure journals
        FAILED (no half-routed state), and after the target restarts a
        re-driven migration converges with every acked write present
        (reference tests-fuzz/targets/migration/)."""
        from greptimedb_tpu.datatypes import (
            ColumnSchema, ConcreteDataType as T, Schema, SemanticType as S,
        )
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.frontend import RemoteDatanode

        storage = str(tmp_path / "store")
        wal = str(tmp_path / "broker")

        def start_node(i):
            p = subprocess.Popen(
                [sys.executable, "-m", "greptimedb_tpu.cli", "datanode",
                 "start", "--node-id", str(i), "--data-home", storage,
                 "--remote-wal-dir", wal, "--managed", "--platform", "cpu"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd="/root/repo")
            addr = json.loads(p.stdout.readline())["address"]
            return p, addr

        procs = {}
        procs[0], a0 = start_node(0)
        procs[1], a1 = start_node(1)
        try:
            sch = Schema((
                ColumnSchema("h", T.STRING, S.TAG),
                ColumnSchema("ts", T.TIMESTAMP_MILLISECOND, S.TIMESTAMP),
                ColumnSchema("v", T.FLOAT64, S.FIELD),
            ))
            ms = Metasrv(MemoryKv())
            p0 = RemoteDatanode(0, a0)
            p1 = RemoteDatanode(1, a1)
            ms.register_datanode(p0)
            ms.register_datanode(p1)
            rid = 888
            p0.handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "leader",
                 "schema": sch.to_dict()}, 0.0)
            ms.set_region_route(rid, 0)
            for k in range(25):
                p0.write(rid, {"h": [f"h{k % 3}"], "ts": [1000 + k],
                               "v": [float(k)]}, float(k))
            acked = 25
            # kill the TARGET right before the migration runs: the very
            # first phase (open_candidate on node 1) hits a dead socket
            procs[1].send_signal(signal.SIGKILL)
            procs[1].wait()
            with pytest.raises(Exception):
                ms.migrate_region(rid, 0, 1, now_ms=100.0)
            # no half-migrated route: reads still serve from node 0
            assert len(p0.read(rid)["ts"]) >= acked
            # target restarts (same storage + remote WAL) and migration
            # re-drives to convergence
            procs[1], a1b = start_node(1)
            p1b = RemoteDatanode(1, a1b)
            ms.datanodes[1] = p1b
            ms.migrate_region(rid, 0, 1, now_ms=200.0)
            host = p1b.read(rid)
            assert len(host["ts"]) >= acked, (len(host["ts"]), acked)
            p1b.write(rid, {"h": ["z"], "ts": [9999], "v": [9.0]}, 300.0)
            assert len(p1b.read(rid)["ts"]) >= acked + 1
            for i, addr in ((0, a0), (1, a1b)):
                try:
                    DatanodeClient(addr).action("shutdown")
                    procs[i].wait(timeout=20)
                except Exception:  # noqa: BLE001
                    pass
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=10)


# ---------------------------------------------------------------------------
# Chaos tier (ISSUE 6): deterministic seed-driven fault injection at every
# remote boundary, survived by the retry/failover machinery.  Fast cases run
# in tier-1; long soak cases are marked slow.
# ---------------------------------------------------------------------------

from greptimedb_tpu.utils.chaos import (  # noqa: E402
    CHAOS, ChaosController, ChaosError, ChaosRule, _parse_rules,
)


@pytest.fixture
def chaos():
    CHAOS.reset()
    yield CHAOS
    CHAOS.reset()


class TestChaosController:
    pytestmark = pytest.mark.chaos

    def test_disabled_is_default_and_noop(self):
        c = ChaosController()
        assert not c.enabled
        for _ in range(1000):
            c.inject("flight.call")  # must never raise or sleep

    def test_env_spec_parses(self):
        seed, rules = _parse_rules(
            "seed=7;flight.call=0.2:error;wal.append=0.1:stall:50;"
            "s3.read=1:error:limit=2")
        assert seed == 7
        assert rules["flight.call"].prob == 0.2
        assert rules["wal.append"].action == "stall"
        assert rules["wal.append"].delay_ms == 50.0
        assert rules["s3.read"].limit == 2

    def test_deterministic_fire_pattern(self):
        def pattern(seed):
            c = ChaosController()
            c.configure(seed, {"p": ChaosRule("p", 0.3)})
            out = []
            for i in range(50):
                try:
                    c.inject("p")
                    out.append(False)
                except ChaosError:
                    out.append(True)
            return out

        a, b = pattern(42), pattern(42)
        assert a == b  # same seed, same faults at the same call indices
        assert any(a) and not all(a)
        assert pattern(43) != a  # a different seed differs somewhere

    def test_limit_caps_fires(self):
        c = ChaosController()
        c.configure(1, {"p": ChaosRule("p", 1.0, limit=3)})
        fired = 0
        for _ in range(10):
            try:
                c.inject("p")
            except ChaosError:
                fired += 1
        assert fired == 3 and c.fired("p") == 3

    def test_points_have_independent_streams(self):
        c = ChaosController()
        c.configure(5, {"a": ChaosRule("a", 1.0, limit=1),
                        "b": ChaosRule("b", 1.0, limit=1)})
        with pytest.raises(ChaosError):
            c.inject("a")
        with pytest.raises(ChaosError):
            c.inject("b")


class TestRetryEnvelope:
    pytestmark = pytest.mark.chaos

    def test_client_survives_injected_flight_faults(self, tmp_path, chaos):
        """Client-side chaos on the wire: the retry envelope absorbs the
        first N faults and the call still succeeds; /metrics counts the
        fault pressure."""
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.datanode import DatanodeFlightServer
        from greptimedb_tpu.utils.telemetry import REGISTRY
        from tests.test_meta import schema

        server = DatanodeFlightServer(0, str(tmp_path / "dn"))
        try:
            client = DatanodeClient(server.address)
            client.instruction({"kind": "open_region", "region_id": 5,
                                "role": "leader",
                                "schema": schema().to_dict()})
            before = REGISTRY.value("greptime_remote_retry_total",
                                    ("flight", "ChaosError"))
            chaos.configure(3, {"flight.call": ChaosRule(
                "flight.call", 1.0, "error", limit=2)})
            client.write(5, {"h": ["a"], "ts": [1000], "v": [1.0]})
            out = client.query("SELECT count(*) FROM t", "t", [5])
            assert out.column("count(*)").to_pylist() == [1]
            assert chaos.fired("flight.call") == 2  # faults DID fire
            after = REGISTRY.value("greptime_remote_retry_total",
                                   ("flight", "ChaosError"))
            assert after - before >= 2  # ...and were counted as retries
            client.close()
        finally:
            chaos.reset()
            server.shutdown()

    def test_exhausted_retries_surface(self, tmp_path, chaos):
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.datanode import DatanodeFlightServer

        server = DatanodeFlightServer(0, str(tmp_path / "dn"))
        try:
            client = DatanodeClient(server.address, max_retries=2)
            chaos.configure(3, {"flight.call": ChaosRule(
                "flight.call", 1.0, "error")})  # unbounded
            with pytest.raises(ChaosError):
                client.action("status")
        finally:
            chaos.reset()
            server.shutdown()

    def test_frontend_route_retry_survives_server_fault(self, tmp_path,
                                                        chaos):
        """Server-side chaos (fault inside the datanode handler, NOT
        retryable at the transport layer): the frontend's route-refresh
        retry absorbs exactly one, per the satellite contract."""
        from greptimedb_tpu.rpc.datanode import DatanodeFlightServer
        from greptimedb_tpu.rpc.frontend import DistFrontend
        from greptimedb_tpu.utils.telemetry import REGISTRY

        server = DatanodeFlightServer(0, str(tmp_path / "dn"))
        fe = DistFrontend()
        try:
            fe.add_datanode(0, server.address)
            fe.sql("CREATE TABLE rt (h STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " v DOUBLE, PRIMARY KEY (h))")
            fe.sql("INSERT INTO rt VALUES ('a', 1000, 1.0)")
            before = REGISTRY.value("greptime_frontend_route_retry_total",
                                    ("select",))
            chaos.configure(9, {"datanode.call": ChaosRule(
                "datanode.call", 1.0, "error", limit=1)})
            res = fe.sql("SELECT count(*) FROM rt")
            assert res.rows == [[1]]
            assert chaos.fired("datanode.call") == 1
            after = REGISTRY.value("greptime_frontend_route_retry_total",
                                   ("select",))
            assert after - before == 1
            # write path has the same one-retry contract
            chaos.configure(9, {"datanode.call": ChaosRule(
                "datanode.call", 1.0, "error", limit=1)})
            fe.sql("INSERT INTO rt VALUES ('b', 2000, 2.0)")
            assert fe.sql("SELECT count(*) FROM rt").rows == [[2]]
        finally:
            chaos.reset()
            fe.close()
            server.shutdown()

    def test_s3_retry_counter_shares_registry(self, chaos):
        """Injected S3 read faults are survived by the store's retry loop
        and counted in the SAME greptime_remote_retry_total counter as
        flight retries (satellite: /metrics shows fault pressure)."""
        from greptimedb_tpu.storage.s3 import MockS3Server, S3ObjectStore
        from greptimedb_tpu.utils.telemetry import REGISTRY

        mock = MockS3Server()
        try:
            store = S3ObjectStore(mock.endpoint, "bkt", access_key="k",
                                  secret_key="s")
            store.write("region_1/sst/x.parquet", b"DATA")
            before = REGISTRY.value("greptime_remote_retry_total",
                                    ("s3", "ChaosError"))
            chaos.configure(4, {"s3.read": ChaosRule(
                "s3.read", 1.0, "error", limit=2)})
            assert store.read("region_1/sst/x.parquet") == b"DATA"
            after = REGISTRY.value("greptime_remote_retry_total",
                                   ("s3", "ChaosError"))
            assert after - before == 2
        finally:
            chaos.reset()
            mock.stop()

    def test_wal_append_stall_only_delays(self, tmp_path, chaos):
        from greptimedb_tpu.storage.remote_wal import (
            RemoteLogStore, SharedLogBroker,
        )

        broker = SharedLogBroker(str(tmp_path / "b"))
        store = RemoteLogStore(broker, region_id=1)
        chaos.configure(2, {"wal.append": ChaosRule(
            "wal.append", 1.0, "stall", delay_ms=5.0, limit=3)})
        for seq in range(1, 5):
            store.append(seq, b"x")  # stalls, never fails
        assert chaos.fired("wal.append") == 3
        assert [s for s, _p in store.replay(0)] == [1, 2, 3, 4]


class TestChaosUnderLoad:
    """The flagship acceptance scenario: kill the leader datanode during
    a closed-loop query workload with fault injection seeded.  Zero
    acked-write loss (remote-WAL replay), every query correct (retry +
    failover routing), bounded-staleness follower reads, and the region
    re-served by the survivor without manual intervention."""

    pytestmark = pytest.mark.chaos

    def _cluster(self, tmp_path):
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.datanode import DatanodeFlightServer
        from greptimedb_tpu.rpc.frontend import DistFrontend

        shared = str(tmp_path / "store")
        wal = str(tmp_path / "broker")
        servers = [
            DatanodeFlightServer(i, shared, managed=True,
                                 remote_wal_dir=wal)
            for i in range(2)
        ]
        kv = MemoryKv()
        ms = Metasrv(kv)
        fe = DistFrontend(kv=kv)
        for s in servers:
            ms.register_datanode(fe.add_datanode(s.node_id, s.address))
        return servers, ms, fe

    def test_kill_leader_mid_bench(self, tmp_path, chaos):
        servers, ms, fe = self._cluster(tmp_path)
        proxies = ms.datanodes
        try:
            # the first flight calls get injected faults (fully
            # deterministic: prob 1 with a fire limit), survived by the
            # client retry envelope
            chaos.configure(11, {"flight.call": ChaosRule(
                "flight.call", 1.0, "error", limit=3)})
            fe.sql("CREATE TABLE ct (h STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " v DOUBLE, PRIMARY KEY (h))")
            info = fe.catalog.get_table("public", "ct")
            rid = info.region_ids[0]
            assert fe.region_route(rid) == 0  # round-robin landed on 0
            ms.add_follower(rid, 1, now_ms=0.0)

            def beat(t, alive=(0, 1)):
                for i in alive:
                    hb = proxies[i].heartbeat(t)
                    for instr in ms.handle_heartbeat(hb, t):
                        proxies[i].handle_instruction(instr, t)

            acked = 0
            t = 0.0
            killed = False
            for k in range(20):
                beat(t, alive=(0, 1) if not killed else (1,))
                try:
                    fe.sql(f"INSERT INTO ct VALUES ('h{k % 3}', "
                           f"{1000 + k}, {float(k)})")
                    acked += 1
                except Exception:  # noqa: BLE001 — leader just died
                    assert killed, "only the kill may fail a write"
                    # the supervision loop (NOT a human) recovers: the
                    # detector has seen 2 minutes of silence
                    migrated = ms.tick(t)
                    assert migrated and migrated[0]["to_node"] == 1
                    fe.sql(f"INSERT INTO ct VALUES ('h{k % 3}', "
                           f"{1000 + k}, {float(k)})")
                    acked += 1
                # closed-loop correctness probe: leader reads are exact
                res = fe.sql("SELECT count(*) FROM ct")
                assert res.rows == [[acked]], f"iteration {k}"
                if k == 9 and not killed:
                    servers[0].shutdown()  # node death mid-bench
                    killed = True
                    t += 120_000.0  # silence the detector observes
                t += 1000.0
            assert killed and acked == 20
            assert chaos.fired("flight.call") == 3  # faults really fired
            # region re-served by the survivor; route swapped in kv
            assert ms.region_route(rid) == 1
            assert proxies[1].roles[rid] == "leader"
            # zero acked loss, bit-level: every acked v value present once
            res = fe.sql("SELECT count(*), min(v), max(v) FROM ct")
            assert res.rows == [[20, 0.0, 19.0]]
        finally:
            chaos.reset()
            fe.close()
            for s in servers:
                try:
                    s.shutdown()
                except Exception:  # noqa: BLE001 — already dead
                    pass

    def test_bounded_staleness_follower_reads(self, tmp_path, chaos):
        from greptimedb_tpu.utils.telemetry import REGISTRY

        servers, ms, fe = self._cluster(tmp_path)
        proxies = ms.datanodes
        try:
            fe.sql("CREATE TABLE ft (h STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " v DOUBLE, PRIMARY KEY (h))")
            rid = fe.catalog.get_table("public", "ft").region_ids[0]
            ms.add_follower(rid, 1, now_ms=0.0)
            for k in range(5):
                fe.sql(f"INSERT INTO ft VALUES ('a', {1000 + k}, "
                       f"{float(k)})")
            # quiesced sync rounds: follower catches up, lag publishes
            t = 0.0
            for _ in range(3):
                for i in (0, 1):
                    hb = proxies[i].heartbeat(t)
                    for instr in ms.handle_heartbeat(hb, t):
                        proxies[i].handle_instruction(instr, t)
                t += 1000.0
            rec = fe.kv.get_json(f"__meta/route/followers/{rid}")
            assert rec["nodes"]["1"]["entries_behind"] == 0
            # follower preference: the read routes to the replica and is
            # correct within the staleness contract (fully synced here).
            # The frontend clock joins the metasrv's deterministic time
            # base — staleness accounting ages the published record
            # against the SAME clock that stamped it.
            fe.clock_ms = lambda: t
            fe.read_preference = "follower"
            fe.max_staleness_ms = 60_000.0
            before = REGISTRY.value("greptime_frontend_read_route_total",
                                    ("follower",))
            assert fe.sql("SELECT count(*) FROM ft").rows == [[5]]
            after = REGISTRY.value("greptime_frontend_read_route_total",
                                   ("follower",))
            assert after - before == 1
            # an unmeetable staleness bound falls back to the leader
            fe.max_staleness_ms = -1.0
            lb = REGISTRY.value("greptime_frontend_read_route_total",
                                ("leader",))
            assert fe.sql("SELECT count(*) FROM ft").rows == [[5]]
            la = REGISTRY.value("greptime_frontend_read_route_total",
                                ("leader",))
            assert la - lb == 1
            # a FROZEN lag record (metasrv stopped publishing) ages out
            # of the contract even though its lag field still reads
            # fresh — bounded staleness, not bounded-at-publication-time
            fe.max_staleness_ms = 60_000.0
            fe.clock_ms = lambda: t + 300_000.0
            lb = REGISTRY.value("greptime_frontend_read_route_total",
                                ("leader",))
            assert fe.sql("SELECT count(*) FROM ft").rows == [[5]]
            la = REGISTRY.value("greptime_frontend_read_route_total",
                                ("leader",))
            assert la - lb == 1
        finally:
            chaos.reset()
            fe.close()
            for s in servers:
                s.shutdown()


class TestChaosSoak:
    """Long soak: repeated kill/recover rounds with broader fault rules.
    Excluded from tier-1 (slow)."""

    pytestmark = [pytest.mark.chaos, pytest.mark.slow]

    def test_repeated_leader_kills_no_acked_loss(self, tmp_path, chaos):
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.datanode import DatanodeFlightServer
        from greptimedb_tpu.rpc.frontend import DistFrontend

        shared = str(tmp_path / "store")
        wal = str(tmp_path / "broker")
        kv = MemoryKv()
        ms = Metasrv(kv)
        fe = DistFrontend(kv=kv)
        servers = {}

        def start(i):
            s = DatanodeFlightServer(i, shared, managed=True,
                                     remote_wal_dir=wal)
            servers[i] = s
            ms.register_datanode(fe.add_datanode(i, s.address))
            return s

        start(0)
        start(1)
        try:
            chaos.configure(SEED, {
                "flight.call": ChaosRule("flight.call", 0.02, "error"),
                "wal.append": ChaosRule("wal.append", 0.05, "stall",
                                        delay_ms=2.0),
            })
            fe.sql("CREATE TABLE sk (h STRING, ts TIMESTAMP(3) TIME INDEX,"
                   " v DOUBLE, PRIMARY KEY (h))")
            rid = fe.catalog.get_table("public", "sk").region_ids[0]
            acked, t = 0, 0.0
            for rnd in range(ROUNDS):
                leader = ms.region_route(rid)
                for k in range(8):
                    try:
                        fe.sql(f"INSERT INTO sk VALUES ('h{k % 4}', "
                               f"{rnd * 100_000 + k}, {float(acked)})")
                        acked += 1
                    except Exception:  # noqa: BLE001
                        ms.tick(t)
                        fe.sql(f"INSERT INTO sk VALUES ('h{k % 4}', "
                               f"{rnd * 100_000 + k}, {float(acked)})")
                        acked += 1
                    for i, s in servers.items():
                        if s is not None:
                            hb = ms.datanodes[i].heartbeat(t)
                            ms.handle_heartbeat(hb, t)
                    t += 1000.0
                assert fe.sql("SELECT count(*) FROM sk").rows == [[acked]]
                # kill the current leader, restart it as a fresh process
                # next round (same shared storage + broker)
                servers[leader].shutdown()
                servers[leader] = None
                # survivors keep a steady cadence while the dead node
                # falls silent (a single time LEAP would pollute the
                # survivors' interval history and mask their next death)
                for _ in range(120):
                    for i, s in servers.items():
                        if s is not None:
                            hb = ms.datanodes[i].heartbeat(t)
                            ms.handle_heartbeat(hb, t)
                    t += 1000.0
                ms.tick(t)
                assert fe.sql("SELECT count(*) FROM sk").rows == [[acked]]
                old = fe.datanodes.pop(leader)
                old.client.close()
                ms.datanodes.pop(leader)
                ms.detectors.pop(leader)
                start(leader)
        finally:
            chaos.reset()
            fe.close()
            for s in servers.values():
                if s is not None:
                    try:
                        s.shutdown()
                    except Exception:  # noqa: BLE001
                        pass


class TestChaosEnvPropagation:
    pytestmark = pytest.mark.chaos

    def test_kill_action_fells_subprocess_datanode(self, tmp_path):
        """GREPTIME_CHAOS in the environment configures the controller at
        import, so OS-process datanodes inherit the test's faults; the
        'kill' action is a SIGKILL analog fired from inside the victim."""
        from greptimedb_tpu.rpc.client import DatanodeClient

        env = dict(os.environ)
        env["GREPTIME_CHAOS"] = "seed=1;datanode.call=1:kill:limit=1"
        p = subprocess.Popen(
            [sys.executable, "-m", "greptimedb_tpu.cli", "datanode",
             "start", "--node-id", "9", "--data-home",
             str(tmp_path / "dn9"), "--platform", "cpu"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            cwd="/root/repo", env=env)
        try:
            addr = json.loads(p.stdout.readline())["address"]
            client = DatanodeClient(addr, max_retries=1, deadline_s=5.0)
            # health is exempt from injection: the probe sees the truth
            assert client.health()
            # the first non-health call triggers the injected kill
            with pytest.raises(Exception):
                client.action("status")
            p.wait(timeout=20)
            assert p.returncode == 137
            assert not DatanodeClient(addr, max_retries=0).health()
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
