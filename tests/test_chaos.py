"""Chaos tier: random SIGKILL against live OS processes, then recovery
invariants (reference tests-fuzz/targets/failover/ + unstable/ — pod
kills under kind; here: process kills under pytest).

Invariants checked after every kill:
  1. no data loss post-WAL-ack: every insert the client saw acknowledged
     is present after reopen (SIGKILL preserves completed write()s);
  2. manifest consistency: every region opens cleanly and scans;
  3. control-plane resume: journaled DDL procedures finish on restart
     and the instance accepts new DDL/DML.

Deterministic by default (seeded); scale with GREPTIME_CHAOS_ROUNDS.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.fuzz

ROUNDS = int(os.environ.get("GREPTIME_CHAOS_ROUNDS", "3"))
SEED = int(os.environ.get("GREPTIME_FUZZ_SEED", "11"))

_INGEST_CHILD = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.storage.region import RegionOptions

home, ack_path = sys.argv[1], sys.argv[2]
db = GreptimeDB(home, region_options=RegionOptions(wal_enabled=True))
db.sql("CREATE TABLE IF NOT EXISTS c (h STRING, ts TIMESTAMP(3) TIME INDEX,"
       " v DOUBLE, PRIMARY KEY (h))")
ack = open(ack_path, "a")
start = int(open(ack_path).read().splitlines()[-1]) + 1 if (
    os.path.getsize(ack_path) > 0) else 0
print("ready", flush=True)
batch = start
while True:
    t0 = 1700000000000 + batch * 10_000
    db.sql("INSERT INTO c VALUES " + ",".join(
        f"('h{i % 5}',{t0 + i},{batch}.0)" for i in range(10)))
    # the WAL append returned: this batch is acked
    ack.write(f"{batch}\n")
    ack.flush()
    os.fsync(ack.fileno())
    batch += 1
"""

_DDL_CHILD = r"""
import random, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.errors import GreptimeError

home, seed = sys.argv[1], int(sys.argv[2])
rng = random.Random(seed)
db = GreptimeDB(home)
print("ready", flush=True)
n = 0
while True:
    name = f"t{rng.randrange(6)}"
    op = rng.random()
    try:
        if op < 0.35:
            db.sql(f"CREATE TABLE IF NOT EXISTS {name} (h STRING, "
                   "ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        elif op < 0.55:
            db.sql(f"DROP TABLE IF EXISTS {name}")
        elif op < 0.7:
            db.sql(f"ALTER TABLE {name} ADD COLUMN c{rng.randrange(4)} "
                   "DOUBLE")
        elif op < 0.85:
            db.sql(f"INSERT INTO {name} VALUES "
                   f"('a', {1700000000000 + n}, 1.0)")
        else:
            db.sql(f"ALTER TABLE {name} SET ttl='{rng.randrange(1, 9)}d'")
    except GreptimeError:
        pass  # typed rejections are legal; crashes are not
    n += 1
"""


def _spawn(code: str, *args) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", code, *args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd="/root/repo",
    )


def _reopen_and_check(home: str):
    """Reopen the data home in-process and verify storage invariants."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from greptimedb_tpu.standalone import GreptimeDB

    db = GreptimeDB(home)
    try:
        for t in db.catalog.list_tables("public"):
            if t.engine not in ("mito",):
                continue
            for region in db._regions_of(t.name):  # lazy open-or-create
                region.scan_host()  # manifest + SSTs + WAL replay coherent
        # the instance still takes DDL + DML after recovery
        db.sql("CREATE TABLE IF NOT EXISTS postcheck (h STRING, "
               "ts TIMESTAMP(3) TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        db.sql("INSERT INTO postcheck VALUES ('x', 1, 1.0)")
        assert db.sql("SELECT count(*) FROM postcheck").num_rows == 1
        db.sql("DROP TABLE postcheck")
        return db
    except Exception:
        db.close()
        raise


class TestIngestKillRecovery:
    def test_no_acked_loss_across_kills(self, tmp_path):
        rng = random.Random(SEED)
        home = str(tmp_path / "chaos")
        ack_path = str(tmp_path / "acked.log")
        open(ack_path, "w").close()
        for rnd in range(ROUNDS):
            p = _spawn(_INGEST_CHILD, home, ack_path)
            assert p.stdout.readline().strip() == "ready"
            # wait for at least one acked batch (first INSERT may pay a
            # jax compile), then a random extra window
            deadline = time.time() + 60
            while os.path.getsize(ack_path) == 0:
                assert time.time() < deadline, "no ack within 60s"
                time.sleep(0.05)
            time.sleep(rng.uniform(0.2, 1.0))  # let more batches flow
            p.send_signal(signal.SIGKILL)
            p.wait()
            acked = [int(l) for l in open(ack_path).read().split()]
            db = _reopen_and_check(home)
            try:
                got = db.sql("SELECT count(*) FROM c").rows[0][0]
                assert got >= len(acked) * 10, (
                    f"round {rnd}: lost acked rows: {got} < "
                    f"{len(acked) * 10}")
                # acked batches are complete (no torn batch visible)
                r = db.sql("SELECT v, count(*) FROM c GROUP BY v")
                for v, cnt in r.rows:
                    if int(v) in set(acked):
                        assert cnt == 10, (v, cnt)
            finally:
                db.close()
            assert len(acked) > 0, "chaos round produced no acked batches"


class TestDdlKillRecovery:
    def test_ddl_procedures_resume(self, tmp_path):
        rng = random.Random(SEED + 1)
        home = str(tmp_path / "ddlchaos")
        for rnd in range(ROUNDS):
            p = _spawn(_DDL_CHILD, home, str(SEED + rnd))
            assert p.stdout.readline().strip() == "ready"
            time.sleep(rng.uniform(0.3, 1.0))
            p.send_signal(signal.SIGKILL)
            p.wait()
            db = _reopen_and_check(home)
            try:
                # procedure journal holds no stuck runners after resume
                from greptimedb_tpu.meta.procedure import (
                    ProcedureManager, ProcedureState,
                )

                pending = [
                    k for k, _v in db.kv.range(ProcedureManager._PREFIX)
                    if json.loads(_v).get("status")
                    == ProcedureState.RUNNING.value
                ]
                assert not pending, pending
            finally:
                db.close()


class TestFailoverChaos:
    def test_random_kill_then_migrate(self, tmp_path):
        """Writes flow to a remote-WAL datanode process; a random-time
        SIGKILL hits it; migration to the second process must expose
        every acked write (reference tests-fuzz/targets/failover/)."""
        from greptimedb_tpu.datatypes import (
            ColumnSchema, ConcreteDataType as T, Schema, SemanticType as S,
        )
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.frontend import RemoteDatanode

        rng = random.Random(SEED + 2)
        storage = str(tmp_path / "store")
        wal = str(tmp_path / "broker")
        procs, addrs = [], []
        for i in range(2):
            p = subprocess.Popen(
                [sys.executable, "-m", "greptimedb_tpu.cli", "datanode",
                 "start", "--node-id", str(i), "--data-home", storage,
                 "--remote-wal-dir", wal, "--managed", "--platform", "cpu"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd="/root/repo")
            procs.append(p)
            addrs.append(json.loads(p.stdout.readline())["address"])
        try:
            sch = Schema((
                ColumnSchema("h", T.STRING, S.TAG),
                ColumnSchema("ts", T.TIMESTAMP_MILLISECOND, S.TIMESTAMP),
                ColumnSchema("v", T.FLOAT64, S.FIELD),
            ))
            ms = Metasrv(MemoryKv())
            proxies = [RemoteDatanode(i, a) for i, a in enumerate(addrs)]
            for pr in proxies:
                ms.register_datanode(pr)
            rid = 777
            proxies[0].handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "leader",
                 "schema": sch.to_dict()}, 0.0)
            ms.set_region_route(rid, 0)
            acked = 0
            kill_after = rng.randrange(3, 12)
            for k in range(40):
                try:
                    proxies[0].write(
                        rid, {"h": [f"h{k % 3}"], "ts": [1000 + k],
                              "v": [float(k)]}, float(k))
                    acked += 1
                except Exception:  # noqa: BLE001 — killed mid-write
                    break
                if rng.random() < 0.2 and k % 5 == 0:
                    proxies[0].client.instruction(
                        {"kind": "flush_region", "region_id": rid})
                if k == kill_after:
                    procs[0].send_signal(signal.SIGKILL)
                    procs[0].wait()
                    break
            ms.migrate_region(rid, 0, 1, now_ms=100.0)
            host = proxies[1].read(rid)
            assert len(host["ts"]) >= acked, (len(host["ts"]), acked)
            # the survivor keeps serving writes
            proxies[1].write(rid, {"h": ["z"], "ts": [9999], "v": [9.0]},
                             200.0)
            assert len(proxies[1].read(rid)["ts"]) >= acked + 1
            DatanodeClient(addrs[1]).action("shutdown")
            procs[1].wait(timeout=20)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=10)


_REPARTITION_CHILD = r"""
import os, random, sys
import jax
jax.config.update("jax_platforms", "cpu")
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.meta.repartition import repartition_table
from greptimedb_tpu.errors import GreptimeError

home, ack_path, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
rng = random.Random(seed)
db = GreptimeDB(home)
db.sql("CREATE TABLE IF NOT EXISTS rp (h STRING, ts TIMESTAMP(3) "
       "TIME INDEX, v DOUBLE, PRIMARY KEY (h)) "
       "PARTITION ON COLUMNS (h) (h < 'm', h >= 'm')")
ack = open(ack_path, "a")
start = int(open(ack_path).read().splitlines()[-1]) + 1 if (
    os.path.getsize(ack_path) > 0) else 0
print("ready", flush=True)
batch = start
rules = [
    (["h"], ["h < 'm'", "h >= 'm'"]),
    (["h"], ["h < 'g'", "h >= 'g' AND h < 't'", "h >= 't'"]),
    ([], []),  # merge to one region
]
while True:
    t0 = 1700000000000 + batch * 100
    db.sql("INSERT INTO rp VALUES " + ",".join(
        f"('{c}{batch % 7}',{t0 + i},{batch}.0)"
        for i, c in enumerate("aghz")))
    ack.write(f"{batch}\n"); ack.flush(); os.fsync(ack.fileno())
    if batch % 3 == 2:
        cols, exprs = rules[rng.randrange(len(rules))]
        try:
            repartition_table(db, "rp", cols, exprs)
        except GreptimeError:
            pass  # same-rule rejection etc.
    batch += 1
"""


class TestRepartitionChaos:
    def test_kill_mid_repartition(self, tmp_path):
        """SIGKILL lands while repartitions (journaled procedures that
        create/retire regions and rewrite routes) interleave with acked
        writes; after reopen the journal must have converged (startup
        resume) and every acked batch must be intact
        (reference tests-fuzz/targets/ddl/fuzz_repartition_table_chaos.rs)."""
        rng = random.Random(SEED + 3)
        home = str(tmp_path / "rpchaos")
        ack_path = str(tmp_path / "acked.log")
        open(ack_path, "w").close()
        for rnd in range(ROUNDS):
            p = _spawn(_REPARTITION_CHILD, home, ack_path, str(SEED + rnd))
            assert p.stdout.readline().strip() == "ready"
            deadline = time.time() + 90
            want = 3 * (rnd + 1)  # let several repartitions happen
            while sum(1 for _ in open(ack_path)) < want:
                assert time.time() < deadline, "no progress within 90s"
                time.sleep(0.05)
            time.sleep(rng.uniform(0.05, 0.6))  # land mid-procedure
            p.send_signal(signal.SIGKILL)
            p.wait()
            acked = [int(l) for l in open(ack_path).read().split()]
            db = _reopen_and_check(home)
            try:
                # journal converged: startup resume left nothing RUNNING
                from greptimedb_tpu.meta.procedure import (
                    ProcedureManager, ProcedureState,
                )

                stuck = [
                    k for k, v in db.kv.range(ProcedureManager._PREFIX)
                    if json.loads(v).get("status")
                    == ProcedureState.RUNNING.value
                ]
                assert not stuck, stuck
                # every acked batch fully present (4 rows each)
                r = db.sql("SELECT v, count(*) FROM rp GROUP BY v")
                got = {int(float(v)): c for v, c in r.rows}
                for b in acked:
                    assert got.get(b) == 4, (rnd, b, got.get(b))
                # the table still accepts writes and repartitions
                from greptimedb_tpu.meta.repartition import (
                    repartition_table,
                )

                db.sql("INSERT INTO rp VALUES ('q', 1, -1.0)")
                repartition_table(db, "rp", ["h"],
                                  ["h < 'x'", "h >= 'x'"])
                assert db.sql(
                    "SELECT count(*) FROM rp WHERE v = -1.0"
                ).rows[0][0] == 1
            finally:
                db.close()


class TestMigrationChaos:
    def test_kill_target_mid_migration(self, tmp_path):
        """The migration TARGET dies while the journaled state machine
        runs (open_candidate → … → close_old); the failure journals
        FAILED (no half-routed state), and after the target restarts a
        re-driven migration converges with every acked write present
        (reference tests-fuzz/targets/migration/)."""
        from greptimedb_tpu.datatypes import (
            ColumnSchema, ConcreteDataType as T, Schema, SemanticType as S,
        )
        from greptimedb_tpu.meta.cluster import Metasrv
        from greptimedb_tpu.meta.kv import MemoryKv
        from greptimedb_tpu.rpc.client import DatanodeClient
        from greptimedb_tpu.rpc.frontend import RemoteDatanode

        storage = str(tmp_path / "store")
        wal = str(tmp_path / "broker")

        def start_node(i):
            p = subprocess.Popen(
                [sys.executable, "-m", "greptimedb_tpu.cli", "datanode",
                 "start", "--node-id", str(i), "--data-home", storage,
                 "--remote-wal-dir", wal, "--managed", "--platform", "cpu"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd="/root/repo")
            addr = json.loads(p.stdout.readline())["address"]
            return p, addr

        procs = {}
        procs[0], a0 = start_node(0)
        procs[1], a1 = start_node(1)
        try:
            sch = Schema((
                ColumnSchema("h", T.STRING, S.TAG),
                ColumnSchema("ts", T.TIMESTAMP_MILLISECOND, S.TIMESTAMP),
                ColumnSchema("v", T.FLOAT64, S.FIELD),
            ))
            ms = Metasrv(MemoryKv())
            p0 = RemoteDatanode(0, a0)
            p1 = RemoteDatanode(1, a1)
            ms.register_datanode(p0)
            ms.register_datanode(p1)
            rid = 888
            p0.handle_instruction(
                {"kind": "open_region", "region_id": rid, "role": "leader",
                 "schema": sch.to_dict()}, 0.0)
            ms.set_region_route(rid, 0)
            for k in range(25):
                p0.write(rid, {"h": [f"h{k % 3}"], "ts": [1000 + k],
                               "v": [float(k)]}, float(k))
            acked = 25
            # kill the TARGET right before the migration runs: the very
            # first phase (open_candidate on node 1) hits a dead socket
            procs[1].send_signal(signal.SIGKILL)
            procs[1].wait()
            with pytest.raises(Exception):
                ms.migrate_region(rid, 0, 1, now_ms=100.0)
            # no half-migrated route: reads still serve from node 0
            assert len(p0.read(rid)["ts"]) >= acked
            # target restarts (same storage + remote WAL) and migration
            # re-drives to convergence
            procs[1], a1b = start_node(1)
            p1b = RemoteDatanode(1, a1b)
            ms.datanodes[1] = p1b
            ms.migrate_region(rid, 0, 1, now_ms=200.0)
            host = p1b.read(rid)
            assert len(host["ts"]) >= acked, (len(host["ts"]), acked)
            p1b.write(rid, {"h": ["z"], "ts": [9999], "v": [9.0]}, 300.0)
            assert len(p1b.read(rid)["ts"]) >= acked + 1
            for i, addr in ((0, a0), (1, a1b)):
                try:
                    DatanodeClient(addr).action("shutdown")
                    procs[i].wait(timeout=20)
                except Exception:  # noqa: BLE001
                    pass
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=10)
