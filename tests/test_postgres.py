"""PostgreSQL wire tests with a minimal hand-rolled v3 client."""

import socket
import struct

import pytest

from greptimedb_tpu.servers.postgres import PostgresServer
from greptimedb_tpu.standalone import GreptimeDB


class MiniPgClient:
    def __init__(self, port: int, database: str | None = None):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        params = {"user": "root"}
        if database:
            params["database"] = database
        body = struct.pack(">I", 196608)
        for k, v in params.items():
            body += k.encode() + b"\x00" + v.encode() + b"\x00"
        body += b"\x00"
        self.sock.sendall(struct.pack(">I", len(body) + 4) + body)
        self._drain_until_ready()

    def _read_msg(self):
        tag = self._recv(1)
        ln = struct.unpack(">I", self._recv(4))[0]
        return tag, self._recv(ln - 4)

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._read_msg()
            msgs.append((tag, body))
            if tag == b"Z":
                return msgs

    def query(self, sql: str):
        body = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack(">I", len(body) + 4) + body)
        names, rows, complete, err = [], [], None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                for _ in range(nf):
                    nul = body.index(b"\x00", pos)
                    names.append(body[pos:nul].decode())
                    pos = nul + 1 + 18
            elif tag == b"D":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                row = []
                for _ in range(nf):
                    ln = struct.unpack(">i", body[pos:pos + 4])[0]
                    pos += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + ln].decode())
                        pos += ln
                rows.append(row)
            elif tag == b"C":
                complete = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body
        return names, rows, complete, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack(">I", 4))
        self.sock.close()


@pytest.fixture(scope="module")
def pg():
    db = GreptimeDB()
    srv = PostgresServer(db, port=0)
    srv.start()
    yield srv
    srv.stop()
    db.close()


class TestPostgresProtocol:
    def test_startup_and_query(self, pg):
        c = MiniPgClient(pg.port)
        names, rows, complete, err = c.query(
            "CREATE TABLE pt (h STRING, ts TIMESTAMP(3) TIME INDEX,"
            " v DOUBLE, PRIMARY KEY (h))")
        assert err is None
        names, rows, complete, err = c.query(
            "INSERT INTO pt VALUES ('a', 1000, 2.5), ('b', 2000, NULL)")
        assert complete == "INSERT 0 2"
        names, rows, complete, err = c.query("SELECT h, v FROM pt ORDER BY h")
        assert names == ["h", "v"]
        assert rows == [["a", "2.5"], ["b", None]]
        assert complete == "SELECT 2"
        c.close()

    def test_error_then_recover(self, pg):
        c = MiniPgClient(pg.port)
        _n, _r, _c, err = c.query("SELECT * FROM nonexistent")
        assert err is not None and b"nonexistent" in err
        names, rows, complete, err = c.query("SELECT 1 + 1")
        assert rows == [["2"]] and err is None
        c.close()

    def test_set_and_ssl_decline(self, pg):
        # SSLRequest then normal startup
        s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
        s.sendall(struct.pack(">II", 8, 80877103))
        assert s.recv(1) == b"N"
        s.close()
        c = MiniPgClient(pg.port)
        _n, _r, complete, err = c.query("SET client_encoding = 'UTF8'")
        assert err is None and complete == "SET"
        c.close()
