"""PostgreSQL wire tests with a minimal hand-rolled v3 client."""

import socket
import struct

import pytest

from greptimedb_tpu.servers.postgres import PostgresServer
from greptimedb_tpu.standalone import GreptimeDB


class MiniPgClient:
    def __init__(self, port: int, database: str | None = None):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        params = {"user": "root"}
        if database:
            params["database"] = database
        body = struct.pack(">I", 196608)
        for k, v in params.items():
            body += k.encode() + b"\x00" + v.encode() + b"\x00"
        body += b"\x00"
        self.sock.sendall(struct.pack(">I", len(body) + 4) + body)
        self._drain_until_ready()

    def _read_msg(self):
        tag = self._recv(1)
        ln = struct.unpack(">I", self._recv(4))[0]
        return tag, self._recv(ln - 4)

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self._read_msg()
            msgs.append((tag, body))
            if tag == b"Z":
                return msgs

    def query(self, sql: str):
        body = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack(">I", len(body) + 4) + body)
        names, rows, complete, err = [], [], None, None
        for tag, body in self._drain_until_ready():
            if tag == b"T":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                for _ in range(nf):
                    nul = body.index(b"\x00", pos)
                    names.append(body[pos:nul].decode())
                    pos = nul + 1 + 18
            elif tag == b"D":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                row = []
                for _ in range(nf):
                    ln = struct.unpack(">i", body[pos:pos + 4])[0]
                    pos += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + ln].decode())
                        pos += ln
                rows.append(row)
            elif tag == b"C":
                complete = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body
        return names, rows, complete, err

    # ---- extended protocol ------------------------------------------
    def _send_msg(self, tag: bytes, body: bytes):
        self.sock.sendall(tag + struct.pack(">I", len(body) + 4) + body)

    def parse(self, name: str, sql: str, oids=()):
        body = (name.encode() + b"\x00" + sql.encode() + b"\x00"
                + struct.pack(">H", len(oids))
                + b"".join(struct.pack(">i", o) for o in oids))
        self._send_msg(b"P", body)

    def bind(self, portal: str, stmt: str, params=(), pformats=(),
             rformats=()):
        body = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
        body += struct.pack(">H", len(pformats))
        body += b"".join(struct.pack(">h", f) for f in pformats)
        body += struct.pack(">H", len(params))
        for p in params:
            if p is None:
                body += struct.pack(">i", -1)
            else:
                raw = p if isinstance(p, bytes) else str(p).encode()
                body += struct.pack(">i", len(raw)) + raw
        body += struct.pack(">H", len(rformats))
        body += b"".join(struct.pack(">h", f) for f in rformats)
        self._send_msg(b"B", body)

    def describe(self, kind: str, name: str):
        self._send_msg(b"D", kind.encode() + name.encode() + b"\x00")

    def execute(self, portal: str = "", max_rows: int = 0):
        self._send_msg(b"E", portal.encode() + b"\x00"
                       + struct.pack(">i", max_rows))

    def sync(self):
        self._send_msg(b"S", b"")
        return self._drain_until_ready()

    @staticmethod
    def collect(msgs):
        """msgs → (names, raw rows (bytes cells), complete, err)."""
        names, rows, complete, err = [], [], None, None
        for tag, body in msgs:
            if tag == b"T":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                for _ in range(nf):
                    nul = body.index(b"\x00", pos)
                    names.append(body[pos:nul].decode())
                    pos = nul + 1 + 18
            elif tag == b"D":
                nf = struct.unpack(">H", body[:2])[0]
                pos = 2
                row = []
                for _ in range(nf):
                    ln = struct.unpack(">i", body[pos:pos + 4])[0]
                    pos += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + ln])
                        pos += ln
                rows.append(row)
            elif tag == b"C":
                complete = body.rstrip(b"\x00").decode()
            elif tag == b"E":
                err = body
        return names, rows, complete, err

    def close(self):
        self.sock.sendall(b"X" + struct.pack(">I", 4))
        self.sock.close()


@pytest.fixture(scope="module")
def pg():
    db = GreptimeDB()
    srv = PostgresServer(db, port=0)
    srv.start()
    yield srv
    srv.stop()
    db.close()


class TestPostgresProtocol:
    def test_startup_and_query(self, pg):
        c = MiniPgClient(pg.port)
        names, rows, complete, err = c.query(
            "CREATE TABLE pt (h STRING, ts TIMESTAMP(3) TIME INDEX,"
            " v DOUBLE, PRIMARY KEY (h))")
        assert err is None
        names, rows, complete, err = c.query(
            "INSERT INTO pt VALUES ('a', 1000, 2.5), ('b', 2000, NULL)")
        assert complete == "INSERT 0 2"
        names, rows, complete, err = c.query("SELECT h, v FROM pt ORDER BY h")
        assert names == ["h", "v"]
        assert rows == [["a", "2.5"], ["b", None]]
        assert complete == "SELECT 2"
        c.close()

    def test_error_then_recover(self, pg):
        c = MiniPgClient(pg.port)
        _n, _r, _c, err = c.query("SELECT * FROM nonexistent")
        assert err is not None and b"nonexistent" in err
        names, rows, complete, err = c.query("SELECT 1 + 1")
        assert rows == [["2"]] and err is None
        c.close()

    def test_extended_text_params(self, pg):
        c = MiniPgClient(pg.port)
        c.query("CREATE TABLE IF NOT EXISTS ept (h STRING, ts TIMESTAMP(3)"
                " TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        # prepared INSERT with $1..$3 (JDBC flow: P/B/D/E/Sync)
        c.parse("ins", "INSERT INTO ept VALUES ($1, $2, $3)",
                oids=(25, 20, 701))
        c.bind("", "ins", params=("x", "1000", "1.5"))
        c.describe("P", "")
        c.execute()
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"1" in tags and b"2" in tags and b"n" in tags
        _, _, complete, err = c.collect(msgs)
        assert err is None and complete == "INSERT 0 1"
        # NULL param via a fresh bind of the same statement
        c.parse("ins2", "INSERT INTO ept VALUES ($1, $2, $3)",
                oids=(25, 20, 701))
        c.bind("", "ins2", params=("y", "2000", None))
        c.execute()
        _, _, complete, err = c.collect(c.sync())
        assert err is None and complete == "INSERT 0 1"
        # prepared SELECT with a text param
        c.parse("sel", "SELECT h, v FROM ept WHERE h = $1", oids=(25,))
        c.bind("", "sel", params=("x",))
        c.describe("P", "")
        c.execute()
        names, rows, complete, err = c.collect(c.sync())
        assert err is None
        assert names == ["h", "v"]
        assert rows == [[b"x", b"1.5"]]
        assert complete == "SELECT 1"
        c.close()

    def test_extended_binary_params_and_results(self, pg):
        c = MiniPgClient(pg.port)
        c.query("CREATE TABLE IF NOT EXISTS ebt (h STRING, ts TIMESTAMP(3)"
                " TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        c.query("INSERT INTO ebt VALUES ('a', 5000, 2.25)")
        # binary int8 + float8 params
        c.parse("q", "SELECT h, v FROM ebt WHERE ts = $1 AND v > $2",
                oids=(20, 701))
        c.bind("", "q",
               params=(struct.pack(">q", 5000), struct.pack(">d", 1.0)),
               pformats=(1, 1), rformats=(0, 1))
        c.execute()
        names, rows, _, err = c.collect(c.sync())
        assert err is None
        assert rows[0][0] == b"a"
        assert struct.unpack(">d", rows[0][1])[0] == 2.25
        c.close()

    def test_extended_describe_statement(self, pg):
        c = MiniPgClient(pg.port)
        c.parse("ds", "SELECT 1 + 1, 'hi'")
        c.describe("S", "ds")  # no bind/execute: just Describe then Sync
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"t" in tags  # ParameterDescription
        names, _, _, err = c.collect(msgs)
        assert err is None and len(names) == 2  # trial-run row schema
        c.close()

    def test_extended_error_recovery(self, pg):
        c = MiniPgClient(pg.port)
        # bind to a statement that was never parsed → error, then the
        # following messages are skipped until Sync
        c.bind("", "ghost", params=())
        c.execute()
        msgs = c.sync()
        _, _, _, err = c.collect(msgs)
        assert err is not None and b"ghost" in err
        # connection still usable, extended and simple both
        c.parse("ok", "SELECT 41 + 1")
        c.bind("", "ok")
        c.execute()
        _, rows, _, err = c.collect(c.sync())
        assert err is None and rows == [[b"42"]]
        names, rows2, _, err = c.query("SELECT 7 * 6")
        assert err is None and rows2 == [["42"]]
        c.close()

    def test_extended_max_rows_suspension(self, pg):
        c = MiniPgClient(pg.port)
        c.query("CREATE TABLE IF NOT EXISTS mrt (h STRING, ts TIMESTAMP(3)"
                " TIME INDEX, PRIMARY KEY (h))")
        c.query("INSERT INTO mrt VALUES ('a',1),('b',2),('c',3),('d',4)")
        c.parse("mr", "SELECT h FROM mrt ORDER BY h")
        c.bind("p1", "mr")
        c.execute("p1", max_rows=3)
        c.execute("p1", max_rows=3)
        msgs = c.sync()
        tags = [t for t, _ in msgs]
        assert b"s" in tags  # PortalSuspended after the first chunk
        _, rows, complete, err = c.collect(msgs)
        assert err is None
        assert [r[0] for r in rows] == [b"a", b"b", b"c", b"d"]
        assert complete == "SELECT 1"  # final chunk had 1 row
        c.close()

    def test_extended_cursor_fetch_across_sync(self, pg):
        # pgJDBC fetchSize pattern: Execute/Sync ... Execute/Sync on the
        # same named portal; suspended portals must survive Sync
        c = MiniPgClient(pg.port)
        c.query("CREATE TABLE IF NOT EXISTS cft (h STRING, ts TIMESTAMP(3)"
                " TIME INDEX, PRIMARY KEY (h))")
        c.query("INSERT INTO cft VALUES ('a',1),('b',2),('c',3)")
        c.parse("cf", "SELECT h FROM cft ORDER BY h")
        c.bind("pc", "cf")
        c.execute("pc", max_rows=2)
        msgs = c.sync()
        assert b"s" in [t for t, _ in msgs]  # suspended
        _, rows1, _, err = c.collect(msgs)
        assert err is None and [r[0] for r in rows1] == [b"a", b"b"]
        c.execute("pc", max_rows=2)  # next fetch, new Sync cycle
        _, rows2, complete, err = c.collect(c.sync())
        assert err is None and [r[0] for r in rows2] == [b"c"]
        assert complete == "SELECT 1"
        # exhausted now → dropped at Sync
        c.execute("pc", max_rows=2)
        _, _, _, err = c.collect(c.sync())
        assert err is not None and b"does not exist" in err
        c.close()

    def test_extended_untyped_numeric_param(self, pg):
        # lib/pq-style: no declared OIDs, text-format numeric params
        c = MiniPgClient(pg.port)
        c.query("CREATE TABLE IF NOT EXISTS unt (h STRING, ts TIMESTAMP(3)"
                " TIME INDEX, v DOUBLE, PRIMARY KEY (h))")
        c.query("INSERT INTO unt VALUES ('a', 1000, 0.5), ('b', 2000, 2.5)")
        c.parse("uq", "SELECT h FROM unt WHERE v > $1 AND ts < $2")
        c.bind("", "uq", params=("1.0", "5000"))
        c.execute()
        _, rows, _, err = c.collect(c.sync())
        assert err is None and rows == [[b"b"]]
        c.close()

    def test_extended_malformed_and_dollar0(self, pg):
        c = MiniPgClient(pg.port)
        # $0 is not a valid placeholder → error at Parse, recover at Sync
        c.parse("z", "SELECT $0")
        _, _, _, err = c.collect(c.sync())
        assert err is not None and b"$0" in err
        # truncated Bind body → ErrorResponse, connection survives
        c._send_msg(b"B", b"no-nul-terminator")
        _, _, _, err = c.collect(c.sync())
        assert err is not None
        _, rows, _, err = c.query("SELECT 5")
        assert err is None and rows == [["5"]]
        c.close()

    def test_set_and_ssl_decline(self, pg):
        # SSLRequest then normal startup
        s = socket.create_connection(("127.0.0.1", pg.port), timeout=5)
        s.sendall(struct.pack(">II", 8, 80877103))
        assert s.recv(1) == b"N"
        s.close()
        c = MiniPgClient(pg.port)
        _n, _r, complete, err = c.query("SET client_encoding = 'UTF8'")
        assert err is None and complete == "SET"
        c.close()
