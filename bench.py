#!/usr/bin/env python
"""TSBS-style benchmark: double-groupby-all (the north-star query).

Reference baseline (BASELINE.md): GreptimeDB v0.12.0 on EC2 c5d.2xlarge
runs TSBS `double-groupby-all` — mean of all 10 CPU metrics grouped by
(hostname, hour) over a 12-hour window at scale=4000 — in 1330.05 ms.

This bench builds the same-shape dataset (4000 hosts, 24 h @ 10 s, 10 f64
metric columns ≈ 34.5 M rows), ingests it through the real write path
(tag encode → memtable → Parquet SST), loads it into the device cache, and
measures steady-state SQL latency of the north-star query (median of 10
runs after 2 warmups — the reference's TSBS numbers are warm medians too).

Prints ONE json line:
  {"metric": "tsbs_double_groupby_all_ms", "value": <median ms>,
   "unit": "ms", "vs_baseline": <value / 1330.05>}   (lower is better)

Env knobs: GREPTIME_BENCH_SCALE (hosts, default 4000),
GREPTIME_BENCH_HOURS (default 24), GREPTIME_BENCH_DATA (cache dir).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np

BASELINE_MS = 1330.05
SCALE = int(os.environ.get("GREPTIME_BENCH_SCALE", "4000"))
HOURS = int(os.environ.get("GREPTIME_BENCH_HOURS", "24"))
# Wall-clock budget: the driver kills the bench with `timeout`; emit the
# JSON line from however many runs completed before the budget expires.
# r03's driver run was allowed >1500s of wall clock; 600 gives a cold
# checkout room for generation + grid build + 10 timed runs + the
# chained promql bench (SIGTERM still emits whatever completed)
BUDGET_S = float(os.environ.get("GREPTIME_BENCH_BUDGET_S", "600"))
START = time.time()
STEP_S = 10
DATA_DIR = os.environ.get(
    "GREPTIME_BENCH_DATA", os.path.join(os.path.dirname(__file__), ".bench_data")
)
METRICS = [
    "usage_user", "usage_system", "usage_idle", "usage_nice", "usage_iowait",
    "usage_irq", "usage_softirq", "usage_steal", "usage_guest",
    "usage_guest_nice",
]
T0 = 1451606400000  # 2016-01-01, the TSBS epoch


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


INGEST_BASELINE_ROWS_S = 326_839.28  # docs/benchmarks/tsbs/v0.12.0.md:15-20
_ingest_rate: list[float] = []  # rows/s, filled by build_db on generation


def _db_dir() -> str:
    # scale-scoped: a reduced-scale TPU retry must never ingest into the
    # full-scale table (mixed-scale data would corrupt every later run)
    return os.path.join(DATA_DIR, f"db_{SCALE}_{HOURS}")


def build_db():
    from greptimedb_tpu.standalone import GreptimeDB
    from greptimedb_tpu.storage.region import RegionOptions

    marker = os.path.join(_db_dir(), f"ready_{SCALE}_{HOURS}")
    db = GreptimeDB(
        _db_dir(),
        # hourly flushes into one 24h TWCS window re-merge the whole window
        # every 8 files — O(N^2) rewriting that ate the r02 budget. The
        # bench's TWCS window matches the flush cadence instead.
        region_options=RegionOptions(wal_enabled=False,
                                     flush_threshold_bytes=1 << 40,
                                     compaction_window_ms=3600 * 1000,
                                     compaction_trigger_files=8),
    )
    cols = ", ".join(f"{m} DOUBLE" for m in METRICS)
    db.sql(
        f"CREATE TABLE IF NOT EXISTS cpu (hostname STRING, "
        f"ts TIMESTAMP(3) TIME INDEX, {cols}, PRIMARY KEY (hostname))"
    )
    if os.path.exists(marker):
        return db

    log(f"generating TSBS data: scale={SCALE}, {HOURS}h @ {STEP_S}s ...")
    region = db._region_of("cpu")
    steps_per_hour = 3600 // STEP_S
    hostnames = np.array([f"host_{i}" for i in range(SCALE)], dtype=object)
    rng = np.random.default_rng(7)
    # random-walk per host, ingested in hour-sized chunks (row-major: for
    # each timestep all hosts report, like the TSBS generator). Generation
    # (rng) is excluded from the measured ingest time — TSBS measures the
    # loader's insert rate, not the generator.
    state = rng.uniform(0, 100, size=(SCALE, len(METRICS)))
    ingest_s = 0.0
    t_wall = time.time()
    for hour in range(HOURS):
        n = SCALE * steps_per_hour
        ts = (
            T0
            + (hour * steps_per_hour + np.repeat(np.arange(steps_per_hour), SCALE))
            * STEP_S * 1000
        )
        hosts = np.tile(hostnames, steps_per_hour)
        data = {"hostname": hosts, "ts": ts}
        walk = rng.normal(0, 1, size=(steps_per_hour, SCALE, len(METRICS)))
        series = np.clip(state[None, :, :] + np.cumsum(walk, axis=0), 0, 100)
        state = series[-1]
        for j, m in enumerate(METRICS):
            data[m] = series[:, :, j].reshape(-1)
        t0 = time.time()
        region.write(data)
        region.flush()
        ingest_s += time.time() - t0
        log(f"  hour {hour + 1}/{HOURS} ingested "
            f"({(hour + 1) * n:,} rows, {time.time() - t_wall:.0f}s wall, "
            f"{(hour + 1) * n / max(ingest_s, 1e-9):,.0f} rows/s ingest)")
    rate = HOURS * SCALE * steps_per_hour / max(ingest_s, 1e-9)
    _ingest_rate.append(rate)
    # persist next to the ready marker: the CPU re-exec child (TPU died
    # mid-query) and post-generation SIGTERMs must still report the rate
    # this build actually measured
    with open(os.path.join(_db_dir(), "ingest_rate.json"), "w") as f:
        json.dump({"rows_per_s": rate}, f)
    with open(marker, "w") as f:
        f.write("ok")
    return db


_times: list[float] = []
_warmup_times: list[float] = []  # SIGTERM fallback when no timed run finished
_emitted = False
_backend = "unknown"
_phase = "startup"  # where a TPU death happened, for the diagnostic
# derived-layout cache counters (set before emit): the perf trajectory
# must attribute warm-query wins to the bucket-major layout, not guess
_extra_stats: dict = {}


def _headline(times: list[float]) -> str:
    value = float(np.median(times))
    line = {
        "metric": "tsbs_double_groupby_all_ms",
        "value": round(value, 2),
        "unit": "ms",
        "vs_baseline": round(value / BASELINE_MS, 4),
        "backend": _backend,
        "runs": len(times),
        "scale": SCALE,
    }
    line.update(_extra_stats)
    if SCALE != 4000:
        # latency scales ~linearly in (series x window) volume on this
        # bandwidth-bound kernel; note it so the number isn't misread
        line["note"] = f"reduced scale {SCALE}/4000; not baseline-comparable"
    return json.dumps(line)


def _ingest_line() -> str | None:
    rate = _ingest_rate[0] if _ingest_rate else None
    if rate is None:
        try:  # measured by an earlier invocation of this same build
            with open(os.path.join(_db_dir(), "ingest_rate.json")) as f:
                rate = float(json.load(f)["rows_per_s"])
        except (OSError, ValueError, KeyError):
            return None
    return json.dumps({
        "metric": "tsbs_ingest_rate",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / INGEST_BASELINE_ROWS_S, 4),
        "backend": "host",
    })


def emit(times: list[float]) -> None:
    """Print the JSON line(s) of record from whatever runs completed.
    Headline metric first; the ingest-rate line follows when this run
    generated data (cached data = nothing honest to report)."""
    global _emitted
    if _emitted or not times:
        return
    _emitted = True
    print(_headline(times), flush=True)
    ing = _ingest_line()
    if ing:
        print(ing, flush=True)


def _on_term(signum, frame):
    # async-signal context: the main thread may hold the stdout/stderr
    # BufferedWriter lock, so print() here could raise a reentrancy error —
    # write the JSON line with raw os.write instead
    global _emitted
    times = _times or _warmup_times[-1:]
    if not _emitted:
        os.write(2, f"signal {signum}; emitting from {len(times)} runs\n".encode())
        if times:
            _emitted = True
            os.write(1, (_headline(times) + "\n").encode())
        ing = _ingest_line()  # ingest happened even if no query finished
        if ing:
            os.write(1, (ing + "\n").encode())
    os._exit(0 if _emitted else 1)


def probe_tpu(
    timeout_s: int = int(os.environ.get("GREPTIME_BENCH_PROBE_S", "60")),
) -> bool:
    """Check the TPU backend responds, CAPTURING the failure mode rather
    than silently falling back (round-3 verdict item #1).  The probe
    subprocess prints phase markers; on hang/death the partial output
    says exactly how far it got (observed failure modes so far:
    jax.devices() blocking indefinitely inside axon PJRT client init —
    no error, the relay's claim leg never completes)."""
    import subprocess

    code = (
        "import jax\n"
        "print('phase: device discovery', flush=True)\n"
        "print('devices:', jax.devices(), flush=True)\n"
        "import jax.numpy as jnp\n"
        "print('phase: 128x128 matmul', flush=True)\n"
        "x = jnp.ones((128,128)); (x @ x).block_until_ready()\n"
        "import numpy as np, jax as j\n"
        "print('phase: 64MB upload', flush=True)\n"
        "d = j.device_put(np.ones((1<<24,), np.float32))\n"
        "d.block_until_ready()\n"
        "print('probe ok', flush=True)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            timeout=timeout_s,
        )
        out, err, timed_out = r.stdout, r.stderr, False
    except subprocess.TimeoutExpired as e:
        out, err, timed_out = e.stdout or b"", e.stderr or b"", True
    text = out.decode(errors="replace")
    if b"probe ok" in out:
        dev_line = next(
            (l for l in text.splitlines() if l.startswith("devices:")), ""
        )
        if "CpuDevice" in dev_line:
            # healthy jax but no accelerator: fall through to the CPU
            # path WITHOUT arming the TPU-retry machinery
            log(f"no TPU backend present ({dev_line.strip()})")
            return False
        return True
    phases = [l for l in text.splitlines() if l.startswith("phase:")]
    last = phases[-1] if phases else "(before device discovery)"
    how = f"hung >{timeout_s}s" if timed_out else "died"
    log(f"TPU DIAG: probe {how} at {last}")
    tail = err.decode(errors="replace").strip().splitlines()[-6:]
    for l in tail:
        log(f"TPU DIAG: stderr: {l}")
    return False


def rerun_on_cpu(reason: str) -> None:
    """The TPU relay can die mid-run (observed: UNAVAILABLE during a bulk
    HBM upload; indefinite hangs in PJRT init). Data generation is cached
    on disk, so a re-exec skips ingest and still emits the JSON line of
    record. First TPU failure at full scale retries TPU once at reduced
    scale (smaller uploads fit under the relay's observed limits); after
    that, CPU. The child inherits stdout — its JSON line IS this
    process's output."""
    import subprocess

    env = dict(os.environ)
    remaining = max(60, int(BUDGET_S - (time.time() - START)))
    env["GREPTIME_BENCH_BUDGET_S"] = str(remaining)
    retry_scale = int(os.environ.get("GREPTIME_BENCH_TPU_RETRY_SCALE", "800"))
    if (_backend not in ("cpu", "unknown") and SCALE > retry_scale
            and "GREPTIME_BENCH_TPU_RETRIED" not in os.environ):
        log(f"TPU DIAG: failed during {_phase} ({reason}); "
            f"retrying TPU at scale={retry_scale}")
        env["GREPTIME_BENCH_TPU_RETRIED"] = "1"
        env["GREPTIME_BENCH_ORIG_SCALE"] = str(SCALE)
        env["GREPTIME_BENCH_SCALE"] = str(retry_scale)
    else:
        log(f"TPU DIAG: failed during {_phase} ({reason}); "
            "re-running on CPU backend")
        env["JAX_PLATFORMS"] = "cpu"
        # a reduced-scale TPU retry must not shrink the CPU number too
        env["GREPTIME_BENCH_SCALE"] = os.environ.get(
            "GREPTIME_BENCH_ORIG_SCALE", str(SCALE)
        )
    r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env)
    raise SystemExit(r.returncode)


def _machine_tag() -> str:
    """Scope the persistent compile cache to this machine: round-3's
    cache carried XLA:CPU AOT artifacts across hosts with different CPU
    features ('could lead to SIGILL' warnings, wrong-machine code)."""
    import hashlib
    import platform

    basis = platform.machine() + platform.processor()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    basis += line
                    break
    except OSError:
        pass
    return hashlib.md5(basis.encode()).hexdigest()[:10]


def prepare_grid(db) -> None:
    """Materialize the resident grid OUTSIDE any timed section: restore
    the host tensors from the on-disk snapshot when the region matches
    (seconds), else build from the SSTs (the expensive path) and persist
    the snapshot for every later invocation on this data dir."""
    global _phase
    from greptimedb_tpu.storage.grid import (
        load_grid_snapshot, save_grid_snapshot,
    )

    region = db._table_view("cpu")
    snap = os.path.join(_db_dir(), "grid_snap")
    t0 = time.time()
    _phase = "grid snapshot restore (device upload)"
    table = load_grid_snapshot(snap, region, mesh=db.mesh)
    if table is not None:
        db.cache.install_grid(region, table)
        log(f"grid restored from snapshot in {time.time() - t0:.0f}s "
            f"({table.nbytes() / 1e9:.2f} GB resident)")
        return
    _phase = "grid build from SSTs (device upload)"
    log("building resident grid from SSTs ...")
    table, _bounds = db.grid_table("cpu", None)
    if table is None:
        log("WARNING: region ineligible for the dense grid; row path")
        return
    if _backend != "cpu":
        # persisting would pull the multi-GB resident tensors BACK through
        # the relay (its observed failure mode is exactly bulk transfers);
        # TPU runs rebuild from SSTs instead
        log(f"grid built in {time.time() - t0:.0f}s (snapshot persist "
            "skipped on accelerator backend)")
        return
    log(f"grid built in {time.time() - t0:.0f}s; persisting snapshot ...")
    try:
        save_grid_snapshot(table, region, snap)
    except OSError as e:
        log(f"snapshot persist failed (non-fatal): {e}")


def cold_scan_bench(db) -> None:
    """Cold-scan A/B (round 10): rebuild the query-ready device table for
    a multi-SST window straight from Parquet — the cold-query/cache-
    rebuild path — once through the streaming scan pipeline (parallel
    decode + code-path tags + sorted-run merge + overlapped upload) and
    once through the sequential reference (GREPTIME_SCAN_THREADS=1 +
    forced lexsort + raw tag decode).  Emits one JSON line with the wall
    clocks, per-phase breakdown and scan counters read from the SAME
    registry /metrics serves, plus a bit-exact parity verdict from a
    smaller window (bounded memory)."""
    import gc

    import greptimedb_tpu.storage.scan as scanmod
    from greptimedb_tpu.storage.cache import build_device_table
    from greptimedb_tpu.utils.telemetry import REGISTRY

    region = db._region_of("cpu")
    nfiles = len(region.sst_files)
    if nfiles < 8:
        log(f"cold-scan bench skipped: only {nfiles} SSTs")
        return
    window_h = min(10, HOURS)
    lo = T0
    hi = T0 + window_h * 3600 * 1000
    seq_env = {
        "GREPTIME_SCAN_THREADS": "1",
        "GREPTIME_SCAN_FORCE_LEXSORT": "1",
        "GREPTIME_SCAN_TAG_CODES": "off",
    }
    # the pipeline leg pins its knobs explicitly ("" = unset-equivalent):
    # ambient operator/debug exports must not silently turn the A/B's
    # fast leg into a second slow leg
    pipe_env = {
        "GREPTIME_SCAN_THREADS": "",
        "GREPTIME_SCAN_FORCE_LEXSORT": "",
        "GREPTIME_SCAN_TAG_CODES": "on",
    }

    def phase_sums() -> dict:
        out: dict = {}
        for name, _kind, _ln, key, child in REGISTRY.snapshot():
            if name == "greptime_scan_phase_seconds":
                out[key[0]] = child.sum
        return out

    def one(env, rng):
        prior = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            p0 = phase_sums()
            t0 = time.time()
            table = build_device_table(region, rng)
            ms = (time.time() - t0) * 1000
            p1 = phase_sums()
            ph = {k: round((p1.get(k, 0.0) - p0.get(k, 0.0)) * 1000, 1)
                  for k in p1}
            return table, ms, ph
        finally:
            for k, v in prior.items():  # restore operator exports
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # untimed warmups: whichever leg runs first must not pay one-time
    # costs the other leg skips — first-touch disk reads (byte sweep)
    # and pyarrow's lazy filtered-read initialization (~1.3 s on the
    # first pq.read_table with filters in a fresh process; small-window
    # build below).  The A/B compares decode+merge+canonicalize+upload.
    small = (lo, lo + 3600 * 1000)
    for m in region.sst_files:
        lp = region.store.local_path(m.path)
        if lp:
            with open(lp, "rb") as f:
                while f.read(1 << 24):
                    pass
    _w, _, _ = one(seq_env, small)
    del _w
    reads0 = REGISTRY.value("greptime_scan_files_total", ("read",))
    # two interleaved rounds per leg, min of each: page-cache/allocator
    # warm-in lands on the first round of BOTH legs instead of biasing
    # whichever ran first.  Pipeline still leads each round, so residual
    # warmup favors the sequential leg — the speedup is a lower bound.
    new_ms = seq_ms = float("inf")
    new_ph: dict = {}
    rows = 0
    merge_path = ""
    files_read = 0
    pipe_obj_rows = 0  # object decodes DURING pipeline legs (pinned 0)
    for _round in range(2):
        obj0 = REGISTRY.value("greptime_scan_object_decode_rows_total")
        table, ms, ph = one(pipe_env, (lo, hi))
        pipe_obj_rows += int(
            REGISTRY.value("greptime_scan_object_decode_rows_total") - obj0)
        if ms < new_ms:
            new_ms, new_ph = ms, ph
            merge_path = scanmod.LAST_MERGE_PATH
        if not rows:
            rows = int(np.asarray(table.row_mask).sum())
            files_read = int(REGISTRY.value(
                "greptime_scan_files_total", ("read",)) - reads0)
        del table
        gc.collect()
        _t, ms, _ph = one(seq_env, (lo, hi))
        seq_ms = min(seq_ms, ms)
        del _t
        gc.collect()

    # parity on a bounded window (both tables resident at once)
    pt, _, _ = one(pipe_env, small)
    st, _, _ = one(seq_env, small)
    parity = "ok"
    for name in pt.columns:
        a = np.asarray(pt.columns[name])
        b = np.asarray(st.columns[name])
        if not np.array_equal(a, b, equal_nan=a.dtype.kind == "f"):
            parity = f"MISMATCH:{name}"
            break
    if pt.dicts != st.dicts:
        parity = "MISMATCH:dicts"
    del pt, st
    gc.collect()

    print(json.dumps({
        "metric": "scan_ms_cold",
        "value": round(new_ms, 1),
        "unit": "ms",
        "scan_ms_cold_seq": round(seq_ms, 1),
        "speedup": round(seq_ms / max(new_ms, 1e-9), 2),
        "files": files_read,
        "rows": rows,
        "merge_path": merge_path,
        "phases_ms": new_ph,
        "scan_threads": scanmod.scan_threads(files_read),
        "scan_rows_total": int(
            REGISTRY.value("greptime_scan_rows_total")),
        "object_decode_rows": pipe_obj_rows,
        "parity": parity,
        "backend": _backend,
    }), flush=True)


def scrub_bench(db, sql) -> None:
    """Scrubber overhead A/B (round 19 acceptance d): warm query medians
    with the background integrity scrubber enabled at PRODUCTION pacing
    (one completed sweep, then interval-gated no-op ticks — the steady
    state a serving node lives in) vs off, plus the disclosed
    during-sweep worst case (a sweep actively verifying multi-MB SSTs
    competes for the container's cores until preemption or the next
    interval gate)."""
    import statistics
    import threading

    from greptimedb_tpu.storage.scrubber import Scrubber

    def median_ms(n=11):
        times = []
        for _ in range(n):
            t0 = time.time()
            db.sql(sql)
            times.append((time.time() - t0) * 1000)
        return statistics.median(times)

    def with_ticker(scrub, fn):
        stop = threading.Event()

        def ticker():
            # the production schedule: one bounded batch per idle tick
            # at the scheduler's 50ms cadence (serving/scheduler.py)
            while not stop.is_set():
                scrub.tick()
                stop.wait(0.05)

        t = threading.Thread(target=ticker, daemon=True)
        t.start()
        try:
            return fn()
        finally:
            stop.set()
            t.join(timeout=30)

    # the bench owns scrub scheduling: the instance's auto-armed
    # scrubber (standalone.py) must not tick during the OFF baseline
    # (warmup's kick_idle may have started the worker pool)
    if getattr(db, "scheduler", None) is not None:
        db.scheduler.idle_hook = None
    off_ms = median_ms()
    # acceptance leg — production steady state: default pacing (a
    # completed sweep, then GREPTIME_SCRUB_INTERVAL_S of gated no-op
    # ticks); must be within noise of off
    scrub = Scrubber(db.regions)
    scrub._resume_skip = 0  # a partial auto-sweep's cursor would skip items
    scrub.run_sweep()  # untimed; the next sweep gates 300s away
    steady_ms = with_ticker(scrub, median_ms)
    # during-sweep worst case, disclosed: continuous verify competing
    # for cores (production sees this for one sweep per interval, and
    # interactive pressure through the scheduler preempts it)
    active = Scrubber(db.regions, interval_s=0, batch=4)
    active._resume_skip = 0
    active_ms = with_ticker(active, median_ms)
    print(json.dumps({
        "metric": "scrub_overhead",
        "warm_ms_scrub_off": round(off_ms, 1),
        "warm_ms_scrub_on": round(steady_ms, 1),
        "ratio": round(steady_ms / max(off_ms, 1e-9), 3),
        "warm_ms_mid_sweep": round(active_ms, 1),
        "mid_sweep_ratio": round(active_ms / max(off_ms, 1e-9), 3),
        "sweeps": scrub.sweeps + active.sweeps,
        "items_verified": scrub.items + active.items,
        "corrupt_found": scrub.corrupt + active.corrupt,
        "backend": _backend,
    }), flush=True)


_COLDSTART_CHILD = r"""
import json, os, sys, time
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")
t_boot = time.time()
from greptimedb_tpu.standalone import GreptimeDB
from greptimedb_tpu.utils.telemetry import REGISTRY

d = sys.argv[1]
hosts, steps = int(sys.argv[2]), int(sys.argv[3])
T0 = 1451606400000
db = GreptimeDB(d)
marker = os.path.join(d, "ready")
if not os.path.exists(marker):
    import numpy as np

    db.sql(
        "CREATE TABLE IF NOT EXISTS cs (h STRING, ts TIMESTAMP(3) "
        "TIME INDEX, v DOUBLE, w DOUBLE, PRIMARY KEY (h))"
    )
    region = db._region_of("cs")
    rng = np.random.default_rng(3)
    n = hosts * steps
    region.write({
        "h": np.repeat([f"host_{i}" for i in range(hosts)], steps),
        "ts": np.tile(T0 + 10_000 * np.arange(steps, dtype=np.int64),
                      hosts),
        "v": rng.uniform(0, 100, n),
        "w": rng.uniform(0, 100, n),
    })
    region.flush()
    with open(marker, "w") as f:
        f.write("ok")
open_ms = (time.time() - t_boot) * 1000
hours = (steps * 10_000) // 3600_000 or 1
sql = (
    "SELECT h, date_trunc('hour', ts) AS hour, avg(v), avg(w) FROM cs "
    f"WHERE ts >= {T0} AND ts < {T0 + hours * 3600_000} "
    "GROUP BY h, hour"
)
t0 = time.time()
r = db.sql(sql)
first_ms = (time.time() - t0) * 1000
t0 = time.time()
db.sql(sql)
warm_ms = (time.time() - t0) * 1000
print(json.dumps({
    "open_ms": round(open_ms, 1),
    "first_query_ms": round(first_ms, 1),
    "warm_ms": round(warm_ms, 1),
    "rows": r.num_rows,
    "xla_builds": int(REGISTRY.value(
        "greptime_compile_xla_builds_total", ("sql",))),
    "aot_hits": db.plan_compiler.aot_hits,
}), flush=True)
db.close()
"""


def cold_start_bench() -> None:
    """First-warm-class-query cold-start A/B (compile/ subsystem): three
    fresh processes over one small dataset — seed (cache on, journals +
    persists the warm class), cache OFF (every kernel recompiles), cache
    ON second boot (AOT warmup, zero XLA builds).  Emits one JSON line:
    ``first_query_ms`` is the served latency of the first warm-class
    query on the warmed boot; ``first_query_ms_off`` the same query's
    latency when the process must compile."""
    import subprocess

    d = os.path.join(DATA_DIR, "coldstart")
    os.makedirs(d, exist_ok=True)
    hosts, steps = 64, 360  # ~23k rows: compile cost dominates, not data

    def run(env_extra):
        env = dict(os.environ, **env_extra)
        if _backend == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-c", _COLDSTART_CHILD, d, str(hosts),
             str(steps)],
            capture_output=True, timeout=600, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        out = r.stdout.decode().strip()
        if r.returncode != 0 or not out:
            tail = r.stderr.decode(errors="replace").strip()[-400:]
            raise RuntimeError(
                f"cold-start child rc={r.returncode}: {tail}")
        return json.loads(out.splitlines()[-1])

    try:
        seed = run({"GREPTIME_COMPILE_CACHE": "on"})
        off = run({"GREPTIME_COMPILE_CACHE": "off"})
        on = run({"GREPTIME_COMPILE_CACHE": "on"})
    except Exception as e:  # noqa: BLE001 — headline already emitted
        log(f"cold-start bench skipped: {e!r}")
        return
    print(json.dumps({
        "metric": "first_query_ms",
        "value": on["first_query_ms"],
        "unit": "ms",
        "first_query_ms_off": off["first_query_ms"],
        "speedup": round(
            off["first_query_ms"] / max(on["first_query_ms"], 1e-9), 2),
        "open_ms_on": on["open_ms"],
        "open_ms_off": off["open_ms"],
        "warm_ms": on["warm_ms"],
        "xla_builds_on": on["xla_builds"],
        "xla_builds_off": off["xla_builds"],
        "aot_hits_on": on["aot_hits"],
        "seed_first_query_ms": seed["first_query_ms"],
        "backend": _backend,
    }), flush=True)


def emit_tpu_projection() -> None:
    """When the TPU relay is down (observed: PJRT init hang, every probe
    across rounds 4-5), record the HLO cost-model projection of the
    north-star kernel instead of nothing (round-4 verdict item 1's
    fallback): compile the EXACT aligned-window kernel shape, read XLA's
    bytes-accessed/flops, and divide by v5e HBM bandwidth (819 GB/s per
    chip; the kernel is bandwidth-bound by 200x)."""
    try:
        import jax
        import jax.numpy as jnp

        S, W, C = 4096, 4320, 10  # full TSBS scale, 12h window
        nb, r = 12, 360
        T = 10240

        def kern(values, valid, s0):
            ones_r = jnp.ones((r,), jnp.float32)
            sums = [
                jax.lax.dynamic_slice_in_dim(values[c], s0, W, axis=1)
                .reshape(S, nb, r) @ ones_r
                for c in range(C)
            ]
            cnt = jax.lax.dynamic_slice_in_dim(valid, s0, W, axis=1).astype(
                jnp.float32).reshape(S, nb, r) @ ones_r
            return [jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), jnp.nan)
                    for s in sums]

        comp = jax.jit(kern).lower(
            jnp.zeros((C, S, T), jnp.float32),
            jnp.zeros((S, T), bool), np.int32(0),
        ).compile()
        ca = comp.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        bytes_acc = float(ca.get("bytes accessed", 0.0))
        flops = float(ca.get("flops", 0.0))
        if bytes_acc <= 0:
            return
        chips = 8
        proj_ms = bytes_acc / (819e9 * chips) * 1000
        print(json.dumps({
            "metric": "tsbs_double_groupby_all_projected_v5e8_ms",
            "value": round(proj_ms, 3),
            "unit": "ms",
            "vs_baseline": round(proj_ms / BASELINE_MS, 6),
            "backend": "cpu-hlo-projection",
            "hlo_bytes_accessed": bytes_acc,
            "hlo_flops": flops,
            "note": "TPU relay down (PJRT init hang, all probes r4-r5); "
                    "projection = HLO bytes / (819 GB/s x 8 chips), "
                    "bandwidth-bound kernel (flops 200x below ceiling)",
        }), flush=True)
    except Exception as e:  # noqa: BLE001 — projection is best-effort
        log(f"tpu projection skipped: {e}")


def main() -> None:
    global _phase
    import jax

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    envp = os.environ.get("JAX_PLATFORMS", "")
    on_cpu = False
    if envp == "cpu":
        # the runtime image preimports jax, so the env var alone is too late
        jax.config.update("jax_platforms", "cpu")
        on_cpu = True
    elif envp and os.environ.get("GREPTIME_BENCH_FORCE_PLATFORM"):
        # operator escape hatch: honor the env var without probing (e.g.
        # a relay slower than the probe timeout that does recover)
        jax.config.update("jax_platforms", envp)
    elif probe_tpu():
        if envp:
            jax.config.update("jax_platforms", envp)
    else:
        log("WARNING: TPU backend unresponsive (diagnostics above); "
            "falling back to CPU for this run")
        orig = os.environ.get("GREPTIME_BENCH_ORIG_SCALE")
        if orig and orig != str(SCALE):
            # reduced-scale TPU retry child whose relay is now fully
            # wedged: the CPU number must be full scale — re-exec
            rerun_on_cpu("probe failed in reduced-scale retry child")
        jax.config.update("jax_platforms", "cpu")
        on_cpu = True

    # Persistent compilation cache, scoped per machine (see _machine_tag):
    # kills the first-run compile on repeat driver invocations.
    cache_dir = os.path.join(DATA_DIR, f"jax_cache_{_machine_tag()}")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache is an optimisation, never a blocker
        log(f"compile cache unavailable: {e}")
    else:
        log(f"compile cache: {os.path.basename(cache_dir)} (machine-scoped;"
            " note: XLA:CPU may still print AOT 'machine feature' mismatch"
            " warnings on SAME-machine loads — pseudo-features like"
            " prefer-no-gather never appear in host detection; benign here"
            " because the dir is keyed to this host's real cpuinfo flags)")

    global _backend
    if not on_cpu:
        _backend = envp or "tpu"  # set BEFORE any op that can wedge: the
        # except path must never query jax (backend init can itself hang)
    try:
        _phase = "data build/ingest"
        db = build_db()
        _backend = jax.default_backend()
        log(f"jax devices: {jax.devices()} "
            f"({time.time() - START:.0f}s elapsed)")
        prepare_grid(db)  # bulk device upload: the relay's favorite crash
    except Exception as e:  # noqa: BLE001
        if on_cpu:
            raise
        rerun_on_cpu(repr(e))

    # TSBS double-groupby-all: avg of all 10 metrics by (hostname, hour)
    # over a 12h window (window shrinks with GREPTIME_BENCH_HOURS)
    window_h = min(12, HOURS)
    q_start = T0 + ((HOURS - window_h) // 2) * 3600 * 1000
    q_end = q_start + window_h * 3600 * 1000
    aggs = ", ".join(f"avg({m})" for m in METRICS)
    sql = (
        f"SELECT hostname, date_trunc('hour', ts) AS hour, {aggs} "
        f"FROM cpu WHERE ts >= {q_start} AND ts < {q_end} "
        f"GROUP BY hostname, hour"
    )

    try:
        _phase = "first query (compile)"
        log("warmup (compile) ...")
        t0 = time.time()
        r = db.sql(sql)
        first_ms = (time.time() - t0) * 1000
        _warmup_times.append(first_ms)
        log(f"  first run: {first_ms:.0f} ms, {r.num_rows} groups")
        expected_groups = SCALE * window_h
        assert r.num_rows == expected_groups, (r.num_rows, expected_groups)

        _phase = "warm second run"
        deadline = START + BUDGET_S
        second_ms = first_ms
        if time.time() < deadline or first_ms < 30_000:
            t0 = time.time()
            db.sql(sql)
            second_ms = (time.time() - t0) * 1000
            _warmup_times.append(second_ms)
            log(f"  second run: {second_ms:.0f} ms")

        # the 10-run warm median is the number of record (round-3 verdict
        # item #2): when each run is affordable, run all 10 regardless of
        # the soft budget — the overshoot is bounded (hard cap below);
        # only genuinely slow runs degrade to however many fit.  Slow runs
        # also leave headroom for the chained promql bench (round-4
        # verdict weak item 1: its line must not be starved out) — cheap
        # runs (<30s) are unaffected by the reservation.
        _phase = "timed runs"
        hard_cap = deadline + 300
        reserve = (0.0 if os.environ.get("GREPTIME_BENCH_NO_PROMQL")
                   else 240.0)
        while len(_times) < 10:
            now = time.time()
            # estimate from the slowest recent run, not just the warm-up:
            # an evicted grid mid-loop must tighten the overshoot bound
            est_ms = max(second_ms, _times[-1] if _times else 0.0)
            affordable = now + est_ms / 1000 < deadline - reserve or (
                est_ms < 30_000 and now + est_ms / 1000 < hard_cap
            )
            if not affordable:
                break
            t0 = time.time()
            r = db.sql(sql)
            _times.append((time.time() - t0) * 1000)
    except AssertionError:
        raise  # wrong RESULTS must never be masked as device loss
    except Exception as e:  # noqa: BLE001 — device loss mid-run
        if _times:
            log(f"device lost after {len(_times)} runs ({e!r}); emitting")
        elif not on_cpu:
            rerun_on_cpu(repr(e))
        else:
            raise

    if not _times:
        # budget exhausted during warmup: the warm(er) run is the number
        _times.append(second_ms)
    log(f"runs: {[f'{t:.0f}' for t in _times]} ms; groups={r.num_rows} "
        f"({time.time() - START:.0f}s elapsed)")
    try:
        # counters come from the telemetry registry — the same numbers
        # /metrics serves — so the bench JSON and a scrape can never
        # disagree (the caches mirror every event into the registry)
        from greptimedb_tpu.utils.telemetry import REGISTRY

        _extra_stats["layout_cache_hits"] = int(REGISTRY.value(
            "greptime_cache_events_total", ("layout", "layout", "hit")))
        _extra_stats["layout_cache_builds"] = int(REGISTRY.value(
            "greptime_cache_events_total", ("layout", "layout", "build")))
        # per-workload quota pressure: the registry mirror of
        # utils/memory.py's rejected counters
        _extra_stats["memory_rejects"] = {
            name: int(REGISTRY.value(
                "greptime_memory_admissions_rejected_total", (name,)))
            for name in db.memory.usage()
            if REGISTRY.value(
                "greptime_memory_admissions_rejected_total", (name,))
        }
    except Exception as e:  # noqa: BLE001 — stats are best-effort
        log(f"layout-cache stats unavailable: {e}")
    emit(_times)
    if _backend == "cpu" and not os.environ.get("GREPTIME_BENCH_NO_PROJ"):
        emit_tpu_projection()
    # cold-scan A/B (round 10): cheap next to the warm loop; still gated
    # on leftover budget so the promql reservation survives
    if (not os.environ.get("GREPTIME_BENCH_NO_SCAN")
            and deadline - time.time() > 120):
        _phase = "cold-scan bench"
        try:
            cold_scan_bench(db)
        except Exception as e:  # noqa: BLE001 — headline already emitted
            log(f"cold-scan bench skipped: {e!r}")
    # scrubber overhead A/B (round 19): warm medians with the verified
    # background sweep hammering vs idle — cheap (reuses the warm query)
    if (not os.environ.get("GREPTIME_BENCH_NO_SCRUB")
            and deadline - time.time() > 60):
        _phase = "scrub-overhead bench"
        try:
            scrub_bench(db, sql)
        except Exception as e:  # noqa: BLE001 — headline already emitted
            log(f"scrub bench skipped: {e!r}")
    db.close()
    # cold-start A/B (round 18): first-warm-class-query latency with the
    # persistent compile cache on vs off, fresh subprocesses
    if (not os.environ.get("GREPTIME_BENCH_NO_COLDSTART")
            and deadline - time.time() > 90):
        _phase = "cold-start bench"
        cold_start_bench()

    # PromQL north star (BASELINE.md target #2): piggyback on leftover
    # budget so the driver's single bench.py invocation records it too;
    # the child prints its own JSON line to the shared stdout
    remaining = deadline - time.time()
    if remaining > 90 and not os.environ.get("GREPTIME_BENCH_NO_PROMQL"):
        env = dict(os.environ,
                   GREPTIME_BENCH_BUDGET_S=str(int(remaining)))
        if remaining < 360 and "GREPTIME_PROMQL_SERIES" not in env:
            # not enough budget for 1M-series generation + compile: a
            # reduced-cardinality line (annotated by the child) beats the
            # r04 outcome of NO promql line in the driver artifact
            env["GREPTIME_PROMQL_SERIES"] = "250000"
        plat = os.environ.get("JAX_PLATFORMS") or (
            "cpu" if _backend == "cpu" else None)
        if plat:
            env["JAX_PLATFORMS"] = plat
        log(f"promql north-star bench ({remaining:.0f}s budget left) ...")
        # EXEC, don't fork: a subprocess would run alongside this
        # process's multi-GB resident grid and jax buffers — observed
        # OOM-killed silently in r5 (child died with no output, the r4
        # 'tail ends at the first JAX warning' signature).  Replacing
        # the process frees everything; stdout stays the same fd so the
        # child's JSON line lands in the same capture.
        try:
            sys.stdout.flush()
            sys.stderr.flush()
            os.execve(
                sys.executable,
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_promql.py")],
                env,
            )
        except Exception as e:  # noqa: BLE001 — headline already emitted
            log(f"promql bench skipped: {e}")


if __name__ == "__main__":
    main()
