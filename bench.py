#!/usr/bin/env python
"""TSBS-style benchmark: double-groupby-all (the north-star query).

Reference baseline (BASELINE.md): GreptimeDB v0.12.0 on EC2 c5d.2xlarge
runs TSBS `double-groupby-all` — mean of all 10 CPU metrics grouped by
(hostname, hour) over a 12-hour window at scale=4000 — in 1330.05 ms.

This bench builds the same-shape dataset (4000 hosts, 24 h @ 10 s, 10 f64
metric columns ≈ 34.5 M rows), ingests it through the real write path
(tag encode → memtable → Parquet SST), loads it into the device cache, and
measures steady-state SQL latency of the north-star query (median of 10
runs after 2 warmups — the reference's TSBS numbers are warm medians too).

Prints ONE json line:
  {"metric": "tsbs_double_groupby_all_ms", "value": <median ms>,
   "unit": "ms", "vs_baseline": <value / 1330.05>}   (lower is better)

Env knobs: GREPTIME_BENCH_SCALE (hosts, default 4000),
GREPTIME_BENCH_HOURS (default 24), GREPTIME_BENCH_DATA (cache dir).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np

BASELINE_MS = 1330.05
SCALE = int(os.environ.get("GREPTIME_BENCH_SCALE", "4000"))
HOURS = int(os.environ.get("GREPTIME_BENCH_HOURS", "24"))
# Wall-clock budget: the driver kills the bench with `timeout`; emit the
# JSON line from however many runs completed before the budget expires.
BUDGET_S = float(os.environ.get("GREPTIME_BENCH_BUDGET_S", "420"))
START = time.time()
STEP_S = 10
DATA_DIR = os.environ.get(
    "GREPTIME_BENCH_DATA", os.path.join(os.path.dirname(__file__), ".bench_data")
)
METRICS = [
    "usage_user", "usage_system", "usage_idle", "usage_nice", "usage_iowait",
    "usage_irq", "usage_softirq", "usage_steal", "usage_guest",
    "usage_guest_nice",
]
T0 = 1451606400000  # 2016-01-01, the TSBS epoch


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


INGEST_BASELINE_ROWS_S = 326_839.28  # docs/benchmarks/tsbs/v0.12.0.md:15-20
_ingest_rate: list[float] = []  # rows/s, filled by build_db on generation


def build_db():
    from greptimedb_tpu.standalone import GreptimeDB
    from greptimedb_tpu.storage.region import RegionOptions

    marker = os.path.join(DATA_DIR, f"ready_{SCALE}_{HOURS}")
    db = GreptimeDB(
        DATA_DIR,
        # hourly flushes into one 24h TWCS window re-merge the whole window
        # every 8 files — O(N^2) rewriting that ate the r02 budget. The
        # bench's TWCS window matches the flush cadence instead.
        region_options=RegionOptions(wal_enabled=False,
                                     flush_threshold_bytes=1 << 40,
                                     compaction_window_ms=3600 * 1000,
                                     compaction_trigger_files=8),
    )
    cols = ", ".join(f"{m} DOUBLE" for m in METRICS)
    db.sql(
        f"CREATE TABLE IF NOT EXISTS cpu (hostname STRING, "
        f"ts TIMESTAMP(3) TIME INDEX, {cols}, PRIMARY KEY (hostname))"
    )
    if os.path.exists(marker):
        return db

    log(f"generating TSBS data: scale={SCALE}, {HOURS}h @ {STEP_S}s ...")
    region = db._region_of("cpu")
    steps_per_hour = 3600 // STEP_S
    hostnames = np.array([f"host_{i}" for i in range(SCALE)], dtype=object)
    rng = np.random.default_rng(7)
    # random-walk per host, ingested in hour-sized chunks (row-major: for
    # each timestep all hosts report, like the TSBS generator). Generation
    # (rng) is excluded from the measured ingest time — TSBS measures the
    # loader's insert rate, not the generator.
    state = rng.uniform(0, 100, size=(SCALE, len(METRICS)))
    ingest_s = 0.0
    t_wall = time.time()
    for hour in range(HOURS):
        n = SCALE * steps_per_hour
        ts = (
            T0
            + (hour * steps_per_hour + np.repeat(np.arange(steps_per_hour), SCALE))
            * STEP_S * 1000
        )
        hosts = np.tile(hostnames, steps_per_hour)
        data = {"hostname": hosts, "ts": ts}
        walk = rng.normal(0, 1, size=(steps_per_hour, SCALE, len(METRICS)))
        series = np.clip(state[None, :, :] + np.cumsum(walk, axis=0), 0, 100)
        state = series[-1]
        for j, m in enumerate(METRICS):
            data[m] = series[:, :, j].reshape(-1)
        t0 = time.time()
        region.write(data)
        region.flush()
        ingest_s += time.time() - t0
        log(f"  hour {hour + 1}/{HOURS} ingested "
            f"({(hour + 1) * n:,} rows, {time.time() - t_wall:.0f}s wall, "
            f"{(hour + 1) * n / max(ingest_s, 1e-9):,.0f} rows/s ingest)")
    rate = HOURS * SCALE * steps_per_hour / max(ingest_s, 1e-9)
    _ingest_rate.append(rate)
    # persist next to the ready marker: the CPU re-exec child (TPU died
    # mid-query) and post-generation SIGTERMs must still report the rate
    # this build actually measured
    with open(os.path.join(DATA_DIR, "ingest_rate.json"), "w") as f:
        json.dump({"rows_per_s": rate}, f)
    with open(marker, "w") as f:
        f.write("ok")
    return db


_times: list[float] = []
_warmup_times: list[float] = []  # SIGTERM fallback when no timed run finished
_emitted = False
_backend = "unknown"


def _headline(times: list[float]) -> str:
    value = float(np.median(times))
    return json.dumps({
        "metric": "tsbs_double_groupby_all_ms",
        "value": round(value, 2),
        "unit": "ms",
        "vs_baseline": round(value / BASELINE_MS, 4),
        "backend": _backend,
        "runs": len(times),
    })


def _ingest_line() -> str | None:
    rate = _ingest_rate[0] if _ingest_rate else None
    if rate is None:
        try:  # measured by an earlier invocation of this same build
            with open(os.path.join(DATA_DIR, "ingest_rate.json")) as f:
                rate = float(json.load(f)["rows_per_s"])
        except (OSError, ValueError, KeyError):
            return None
    return json.dumps({
        "metric": "tsbs_ingest_rate",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / INGEST_BASELINE_ROWS_S, 4),
        "backend": "host",
    })


def emit(times: list[float]) -> None:
    """Print the JSON line(s) of record from whatever runs completed.
    Headline metric first; the ingest-rate line follows when this run
    generated data (cached data = nothing honest to report)."""
    global _emitted
    if _emitted or not times:
        return
    _emitted = True
    print(_headline(times), flush=True)
    ing = _ingest_line()
    if ing:
        print(ing, flush=True)


def _on_term(signum, frame):
    # async-signal context: the main thread may hold the stdout/stderr
    # BufferedWriter lock, so print() here could raise a reentrancy error —
    # write the JSON line with raw os.write instead
    global _emitted
    times = _times or _warmup_times[-1:]
    if not _emitted:
        os.write(2, f"signal {signum}; emitting from {len(times)} runs\n".encode())
        if times:
            _emitted = True
            os.write(1, (_headline(times) + "\n").encode())
        ing = _ingest_line()  # ingest happened even if no query finished
        if ing:
            os.write(1, (ing + "\n").encode())
    os._exit(0 if _emitted else 1)


def probe_tpu(
    timeout_s: int = int(os.environ.get("GREPTIME_BENCH_PROBE_S", "45")),
) -> bool:
    """Check the TPU backend responds (the axon relay can wedge; a hung
    bench is worse than a CPU bench). Probe in a subprocess with timeout."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((128,128));"
        "(x @ x).block_until_ready();"
        "print('ok')"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=timeout_s
        )
        return b"ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def rerun_on_cpu(reason: str) -> None:
    """The TPU relay can die mid-run (observed: UNAVAILABLE during a bulk
    HBM upload). Data generation is cached on disk, so a CPU re-exec
    skips ingest and still emits the JSON line of record. The child
    inherits stdout — its JSON line IS this process's output."""
    import subprocess

    log(f"TPU run failed ({reason}); re-running on CPU backend")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    remaining = max(60, int(BUDGET_S - (time.time() - START)))
    env["GREPTIME_BENCH_BUDGET_S"] = str(remaining)
    r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env)
    raise SystemExit(r.returncode)


def main() -> None:
    import jax

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    if os.environ.get("JAX_PLATFORMS"):
        # the runtime image preimports jax, so the env var alone is too late
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    elif not probe_tpu():
        log("WARNING: TPU backend unresponsive; falling back to CPU for this run")
        jax.config.update("jax_platforms", "cpu")

    # Persistent compilation cache: kills the multi-minute first-run compile
    # on repeat driver invocations (jit programs are keyed by shape class,
    # so the warm cache from data generation runs carries over).
    cache_dir = os.path.join(DATA_DIR, "jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache is an optimisation, never a blocker
        log(f"compile cache unavailable: {e}")

    db = build_db()
    global _backend
    _backend = jax.default_backend()
    log(f"jax devices: {jax.devices()} ({time.time() - START:.0f}s elapsed)")

    # TSBS double-groupby-all: avg of all 10 metrics by (hostname, hour)
    # over a 12h window (window shrinks with GREPTIME_BENCH_HOURS)
    window_h = min(12, HOURS)
    q_start = T0 + ((HOURS - window_h) // 2) * 3600 * 1000
    q_end = q_start + window_h * 3600 * 1000
    aggs = ", ".join(f"avg({m})" for m in METRICS)
    sql = (
        f"SELECT hostname, date_trunc('hour', ts) AS hour, {aggs} "
        f"FROM cpu WHERE ts >= {q_start} AND ts < {q_end} "
        f"GROUP BY hostname, hour"
    )

    on_cpu = jax.default_backend() == "cpu"
    try:
        log("warmup (compile + cache build) ...")
        t0 = time.time()
        r = db.sql(sql)
        first_ms = (time.time() - t0) * 1000
        _warmup_times.append(first_ms)
        log(f"  first run: {first_ms:.0f} ms, {r.num_rows} groups")
        expected_groups = SCALE * window_h
        assert r.num_rows == expected_groups, (r.num_rows, expected_groups)

        deadline = START + BUDGET_S
        second_ms = None
        if time.time() < deadline:
            t0 = time.time()
            db.sql(sql)
            second_ms = (time.time() - t0) * 1000
            _warmup_times.append(second_ms)
            log(f"  second run: {second_ms:.0f} ms")

        while len(_times) < 10 and time.time() + (
            second_ms or first_ms
        ) / 1000 < deadline:
            t0 = time.time()
            r = db.sql(sql)
            _times.append((time.time() - t0) * 1000)
    except AssertionError:
        raise  # wrong RESULTS must never be masked as device loss
    except Exception as e:  # noqa: BLE001 — device loss mid-run
        if _times:
            log(f"device lost after {len(_times)} runs ({e!r}); emitting")
        elif not on_cpu:
            rerun_on_cpu(repr(e))
        else:
            raise

    if not _times:
        # budget exhausted during warmup: the warm(er) run is the number
        _times.append(second_ms if second_ms is not None else first_ms)
    log(f"runs: {[f'{t:.0f}' for t in _times]} ms; groups={r.num_rows} "
        f"({time.time() - START:.0f}s elapsed)")
    emit(_times)
    db.close()


if __name__ == "__main__":
    main()
