#!/usr/bin/env python
"""Mixed-workload SLO soak: the closed-loop observatory acceptance gate.

Runs sustained bulk ingest, a dashboard query storm, log search and a
control-plane streaming flow simultaneously — with the integrity
scrubber, AOT warmup and journal drains underneath on the budgeted idle
economy, and one live flow failover mid-soak — then induces a latency
storm (objective override) and verifies the observatory's closed loop:

  - zero SLO-accounting gaps: every scheduler-submitted query that got
    past admission lands in EXACTLY one (tenant, class, protocol)
    sketch (``slo.total_recorded()`` vs the bench's own count);
  - burn-rate alerts FIRE during the induced storm (fast 1h/5m pair),
    background admission is closed while they fire, and the alerts
    CLEAR once the storm passes;
  - background idle consumers show nonzero grants with no consumer
    starved;
  - warm dashboard medians are unchanged with ``GREPTIME_SLO=off``
    (A/B: a second instance on the same data with the observatory
    never imported).

Gates on p99/SLO assertions, not solo medians.  Prints ONE json line
and writes it to ``BENCH_r18.json`` (override the path with
``GREPTIME_BENCH_OUT``; empty disables the file).

Env knobs: GREPTIME_BENCH_SOAK_S (mixed phase, default 6),
GREPTIME_BENCH_STORM_S (default 3), GREPTIME_BENCH_SCALE (hosts,
default 12), GREPTIME_BENCH_CLIENTS (dashboard clients, default 2).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

# observatory knobs land BEFORE any greptimedb_tpu import (setdefault:
# the slow-tier test and operators can override)
os.environ.setdefault("GREPTIME_SLO_SLOT_S", "0.5")  # 5m window = 2.5 s
os.environ.setdefault("GREPTIME_SLO_MIN_SAMPLES", "25")
os.environ.setdefault("GREPTIME_SLO_THRESHOLD_MS", "500")
os.environ.setdefault("GREPTIME_SCRUB", "on")
os.environ.setdefault("GREPTIME_SCRUB_INTERVAL_S", "0")

SOAK_S = float(os.environ.get("GREPTIME_BENCH_SOAK_S", "6"))
STORM_S = float(os.environ.get("GREPTIME_BENCH_STORM_S", "3"))
SCALE = int(os.environ.get("GREPTIME_BENCH_SCALE", "12"))
CLIENTS = int(os.environ.get("GREPTIME_BENCH_CLIENTS", "2"))
T0 = 1451606400000
STEP_MS = 10_000
MINUTES = 20


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_db(home: str):
    import numpy as np

    from greptimedb_tpu.standalone import GreptimeDB

    db = GreptimeDB(home)
    db.sql("CREATE TABLE cpu (hostname STRING, ts TIMESTAMP(3) TIME "
           "INDEX, v0 DOUBLE, v1 DOUBLE, v2 DOUBLE, "
           "PRIMARY KEY (hostname))")
    db.sql("CREATE TABLE logs (app STRING, ts TIMESTAMP(3) TIME INDEX, "
           "line STRING, PRIMARY KEY (app))")
    rng = np.random.default_rng(7)
    samples = MINUTES * 60_000 // STEP_MS
    rows = []
    for h in range(SCALE):
        for i in range(samples):
            v = rng.uniform(0, 100, 3)
            rows.append(f"('host_{h}', {T0 + i * STEP_MS}, "
                        f"{v[0]:.2f}, {v[1]:.2f}, {v[2]:.2f})")
    for c in range(0, len(rows), 500):
        db.sql("INSERT INTO cpu VALUES " + ",".join(rows[c:c + 500]))
    lrows = []
    words = ["GET", "POST", "timeout", "error", "refused", "ok"]
    for i in range(2000):
        w = words[i % len(words)]
        lrows.append(f"('svc-{i % 8}', {T0 + i * 500}, "
                     f"'req {i} {w} /api/v{i % 3}')")
    for c in range(0, len(lrows), 500):
        db.sql("INSERT INTO logs VALUES " + ",".join(lrows[c:c + 500]))
    # flush so the scrubber has SSTs to verify on idle capacity
    db.sql("ADMIN flush_table('cpu')")
    db.sql("ADMIN flush_table('logs')")
    return db


def dash_sql(i: int) -> str:
    lo = T0 + (i % MINUTES) * 60_000
    return (f"SELECT hostname, avg(v0), max(v1) FROM cpu "
            f"WHERE ts >= {lo} AND ts < {lo + 300_000} GROUP BY hostname")


LOG_SQL = "SELECT count(line) FROM logs WHERE line LIKE '%timeout%'"


class Counted:
    """Thread-safe submit wrapper enforcing the accounting rule: a
    submit that got PAST admission (returned, or raised anything but
    ResourcesExhausted) must land in exactly one sketch."""

    def __init__(self, sched):
        from greptimedb_tpu.errors import ResourcesExhausted

        self.sched = sched
        self._RE = ResourcesExhausted
        self._lock = threading.Lock()
        self.recorded_expected = 0
        self.rejected = 0
        self.errors = 0

    def submit(self, sql: str, **kw):
        held = kw.pop("held", False)
        hold: list = [] if held else None
        try:
            r = self.sched.submit(sql, slo_hold=hold, **kw)
        except self._RE:
            with self._lock:
                self.rejected += 1
            return None
        except Exception:  # noqa: BLE001 — errored entries still record
            with self._lock:
                self.recorded_expected += 1
                self.errors += 1
            return None
        if held:
            # the http serialization twin: the sample covers the full
            # submit -> bytes-ready span
            self.sched.record_held(hold)
        with self._lock:
            self.recorded_expected += 1
        return r


def run_phase(counted, duration_s: float, protocols=("http",)):
    """CLIENTS dashboard clients + 1 log-search client + 1 ingest
    client, closed-loop for duration_s; returns latencies (ms)."""
    stop_at = time.perf_counter() + duration_s
    lat: list[list[float]] = [[] for _ in range(CLIENTS)]

    def dash(ci: int):
        i = ci
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            counted.submit(dash_sql(i), protocol="http", held=(i % 2 == 0))
            lat[ci].append((time.perf_counter() - t0) * 1000)
            i += 1

    def logsearch():
        while time.perf_counter() < stop_at:
            counted.submit(LOG_SQL, protocol="sql")
            time.sleep(0.01)

    def ingest():
        i = 0
        while time.perf_counter() < stop_at:
            ts = T0 + (MINUTES * 60_000) + i * 1000
            counted.submit(
                f"INSERT INTO cpu VALUES ('host_0', {ts}, 1.0, 2.0, 3.0)",
                protocol="http")
            i += 1
            time.sleep(0.005)

    threads = [threading.Thread(target=dash, args=(ci,))
               for ci in range(CLIENTS)]
    threads.append(threading.Thread(target=logsearch))
    threads.append(threading.Thread(target=ingest))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [v for lane in lat for v in lane]


def pct(xs, p):
    import numpy as np

    return float(np.percentile(np.asarray(xs), p)) if xs else None


def ab_warm_medians(sched_on, sched_off, rounds: int = 6,
                    per: int = 25) -> tuple[float, float]:
    """Interleaved A/B warm medians: alternating batches on the two
    instances so machine-wide drift (GC, other tenants of the box)
    lands on both sides instead of biasing whichever ran second.
    Measured on the logs table — the soak's ingest thread grows cpu on
    the ON instance only, which would skew a cpu-table comparison."""
    import numpy as np

    for s in (sched_on, sched_off):
        for _ in range(10):
            s.submit(LOG_SQL)
    on: list[float] = []
    off: list[float] = []
    for _ in range(rounds):
        for s, xs in ((sched_on, on), (sched_off, off)):
            for _ in range(per):
                t0 = time.perf_counter()
                s.submit(LOG_SQL)
                xs.append((time.perf_counter() - t0) * 1000)
    return (float(np.median(np.asarray(on))),
            float(np.median(np.asarray(off))))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from greptimedb_tpu.flow.cluster import FlowControlPlane, Flownode
    from greptimedb_tpu.query.parser import parse_sql
    from greptimedb_tpu.utils.telemetry import REGISTRY

    base = tempfile.mkdtemp(prefix="soak_")
    t_build = time.time()
    db = build_db(os.path.join(base, "on"))
    log(f"built soak db ({time.time() - t_build:.0f}s)")
    sched, slo, eco = db.scheduler, db.slo, db.idle_economy
    assert sched is not None and slo is not None and eco is not None, (
        "bench_soak needs the scheduler + SLO observatory armed")

    # control-plane streaming flow over the live cpu table, 2 flownodes
    plane = FlowControlPlane(db.kv)
    nodes = [Flownode(i, db) for i in range(2)]
    t0ms = time.time() * 1000.0
    for n in nodes:
        plane.register_flownode(n)
        n.heartbeat(t0ms)
    plane.create_flow(parse_sql(
        "CREATE FLOW soak_flow SINK TO cpu_agg AS "
        "SELECT count(v0) FROM cpu")[0])
    owner = plane.nodes[plane.route("soak_flow")]
    survivor = next(n for n in plane.nodes.values() if n is not owner)
    plane.run_all()
    owner.engine.checkpoint_now()

    counted = Counted(sched)
    base_recorded = slo.total_recorded()

    # ---- phase 1: mixed workload, one live failover mid-phase --------
    log(f"phase mixed: {CLIENTS}+2 clients x {SOAK_S}s ...")
    half = SOAK_S / 2
    lat1 = run_phase(counted, half)
    owner.alive = False
    survivor.heartbeat(time.time() * 1000.0)
    moved = plane.tick()
    failover_ok = moved == ["soak_flow"] and \
        survivor.engine.ckpt_epoch is not None
    log(f"  failover moved={moved} epoch={survivor.engine.ckpt_epoch}")
    plane.run_all()
    lat1 += run_phase(counted, half)
    p99_mixed = pct(lat1, 99)

    # ---- phase 2: induced latency storm ------------------------------
    # the alert is polled WHILE the storm runs (the honest semantics —
    # and robust to low storm throughput under contention: one
    # post-storm sample can catch a short window below min_samples)
    log(f"phase storm: objective override x {STORM_S}s ...")
    slo.set_objective("default", 0.01)  # everything breaches
    storm = threading.Thread(target=run_phase,
                             args=(counted, STORM_S + 2.0))
    storm.start()
    alerts: list = []
    alert_fired = False
    poll_until = time.perf_counter() + STORM_S + 1.5
    while time.perf_counter() < poll_until:
        time.sleep(0.25)
        alerts = slo.alerts()
        if any(a["severity"] == "fast" for a in alerts):
            alert_fired = True
            break
    if os.environ.get("GREPTIME_BENCH_DEBUG"):
        from greptimedb_tpu.serving.slo import _WINDOWS
        sid = int(slo.clock() / slo.slot_s)
        for k, st in slo._keys.items():
            wins = {w: st.window(sid, n) for w, n in _WINDOWS.items()}
            log(f"  DEBUG {k}: sid={sid} wins={wins} "
                f"min_samples={slo.min_samples}")
    log(f"  alerts firing: {alerts}")
    # background admission must be CLOSED while the fast pair fires
    # (checked mid-storm, while the alert is live)
    rej0 = REGISTRY.value("greptime_scheduler_rejected_total",
                          ("default", "slo_budget")) or 0
    counted.submit("SELECT count(v0) FROM cpu", priority="background")
    rej1 = REGISTRY.value("greptime_scheduler_rejected_total",
                          ("default", "slo_budget")) or 0
    background_rejected = alert_fired and rej1 > rej0
    storm.join()

    # ---- phase 3: recovery — the alert must CLEAR --------------------
    log("phase recover: clean traffic until the short window forgets")
    slo.set_objective("default", 500.0)
    run_phase(counted, 4.0)  # > 5m window (2.5 s) + 1 s alert cache
    time.sleep(1.1)
    alert_cleared = not slo.fast_burn_active()

    # ---- gates --------------------------------------------------------
    recorded = slo.total_recorded() - base_recorded
    accounting_exact = recorded == counted.recorded_expected
    log(f"accounting: recorded={recorded} "
        f"expected={counted.recorded_expected} "
        f"(rejected={counted.rejected} errors={counted.errors})")
    consumers = eco.consumers()
    no_starvation = all(c["starved"] == 0 for c in consumers)
    idle_grants = sum(c["granted"] for c in consumers)
    log(f"idle economy: {consumers}")
    sink_rows = db.sql("SELECT count(*) FROM cpu_agg").rows[0][0]

    # ---- A/B: GREPTIME_SLO=off warm medians --------------------------
    os.environ["GREPTIME_SLO"] = "off"
    try:
        db_off = build_db(os.path.join(base, "off"))
        assert db_off.slo is None and db_off.idle_economy is None
        med_on, med_off = ab_warm_medians(sched, db_off.scheduler)
        db_off.close()
    finally:
        os.environ.pop("GREPTIME_SLO", None)
    ab_ratio = med_on / med_off if med_off else None
    ab_warm_ok = ab_ratio is not None and ab_ratio < 1.5
    log(f"A/B warm median: on={med_on:.2f} ms off={med_off:.2f} ms "
        f"(ratio {ab_ratio:.3f})")

    gates = {
        "accounting_exact": bool(accounting_exact),
        "alert_fired": bool(alert_fired),
        "alert_cleared": bool(alert_cleared),
        "background_rejected": bool(background_rejected),
        "idle_grants_nonzero": bool(idle_grants > 0),
        "no_starvation": bool(no_starvation),
        "failover_moved": bool(failover_ok),
        "flow_sink_live": bool(sink_rows and sink_rows > 0),
        "ab_warm_ok": bool(ab_warm_ok),
    }
    line = {
        "metric": "slo_soak_p99_ms",
        "value": round(p99_mixed, 2) if p99_mixed else None,
        "unit": "ms",
        "gates": gates,
        "recorded": recorded,
        "submitted_recorded": counted.recorded_expected,
        "admission_rejected": counted.rejected,
        "errors": counted.errors,
        "p50_mixed_ms": round(pct(lat1, 50), 2),
        "idle_consumers": {c["name"]: {
            "granted": c["granted"], "elapsed_ms": c["elapsed_ms"],
            "starved": c["starved"]} for c in consumers},
        "idle_throttled": eco.throttled,
        "warm_median_on_ms": round(med_on, 2),
        "warm_median_off_ms": round(med_off, 2),
        "ab_ratio": round(ab_ratio, 3) if ab_ratio else None,
        "status_rows": len(slo.status_rows()),
        "backend": jax.default_backend(),
        "scale": SCALE,
        "soak_s": SOAK_S,
    }
    print(json.dumps(line))
    out = os.environ.get(
        "GREPTIME_BENCH_OUT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r18.json"))
    if out:
        with open(out, "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    db.close()
    failed = [k for k, v in gates.items() if not v]
    if failed:
        log(f"GATE FAILURES: {failed}")
        raise SystemExit(1)
    log("all gates passed")


if __name__ == "__main__":
    main()
